"""Command-line driver: the ``program heat`` analog.

One CLI replaces the reference's seven compiled main programs while keeping
their external contract: discover ``input.dat`` in the working directory,
run the solve, write ``int.dat``/``soln.dat``, print the familiar stdout
lines ("simulation completed!!!!", timing) —
fortran/serial/heat.f90:11-13,50-55,73-83. The reference's build-time
variant choice (which makefile target you compiled) becomes ``--backend`` /
``--variant`` flags; its compile-time ``-DUSE_CUDA/-DNO_AWARE`` become
``--comm``; ``SINGLE_PRECISION`` becomes ``--dtype``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from pathlib import Path
from typing import Optional, Sequence

from .config import HeatConfig, VARIANTS, parse_input, variant_config
from .grid import coords, initial_condition
from .runtime import trace as trace_mod
from .runtime.logging import master_print


def _parse_mesh(s: str):
    try:
        dims = tuple(int(t) for t in s.lower().replace("x", " ").split())
    except ValueError:
        dims = ()
    if not dims or any(d < 1 for d in dims):
        raise argparse.ArgumentTypeError(
            f"mesh must be positive dims like '4x2', got {s!r}")
    return dims


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="heat-tpu",
        description="TPU-native heat-equation framework "
        "(capability rebuild of CUDA-HIP-MPI-Heat-equation-test)",
    )
    sub = p.add_subparsers(dest="command", required=True)

    run = sub.add_parser("run", help="solve the heat equation (input.dat contract)")
    run.add_argument("--input", default="input.dat",
                     help="input.dat path: 'n sigma nu dom_len ntime [soln]'")
    run.add_argument("--variant", choices=sorted(VARIANTS),
                     help="reference-variant preset (sets ic/bc/backend/comm/dtype)")
    run.add_argument("--backend", choices=["serial", "xla", "pallas", "sharded"])
    run.add_argument("--dtype", choices=["float64", "float32", "bfloat16"])
    run.add_argument("--ic", choices=["hat", "hat_half", "hat_small", "uniform", "zero"])
    run.add_argument("--bc", choices=["edges", "ghost", "periodic"])
    run.add_argument("--bc-value", type=float)
    run.add_argument("--ndim", type=int, choices=[2, 3])
    run.add_argument("--comm", choices=["direct", "staged"],
                     help="halo exchange: device-direct (CUDA-aware analog) "
                          "or host-staged (NO_AWARE analog)")
    run.add_argument("--exchange", choices=["seq", "indep", "overlap"],
                     help="ghost-write formulation: axes chained (seq, "
                          "reference-like), all-independent (indep), or "
                          "indep plus interior compute overlapped with the "
                          "halo collectives (overlap; Pallas kernel only); "
                          "bit-identical results")
    run.add_argument("--mesh", type=_parse_mesh,
                     help="device mesh shape, e.g. 4x2 (sharded backend)")
    run.add_argument("--virtual-devices", type=int, metavar="N",
                     help="run on N virtual CPU devices (the reference's "
                          "single-node 'mpirun -np N' development mode, "
                          "fortran/mpi+cuda/makefile:1-2; no hardware needed)")
    run.add_argument("--fuse-steps", type=int,
                     help="pallas temporal blocking depth (0=auto, 1=off)")
    run.add_argument("--local-kernel", choices=["auto", "xla", "pallas"],
                     help="sharded per-shard compute kernel "
                          "(auto = pallas on TPU, xla elsewhere)")
    run.add_argument("--parity-order", action="store_true",
                     help="literal update-then-swap step ordering "
                          "(reference parity, mpi+cuda/heat.F90:206-219)")
    run.add_argument("--heartbeat-every", type=int,
                     help="print 'time_it: i' every k steps (reference prints every step)")
    run.add_argument("--report-sum", action="store_true",
                     help="global temperature sum via psum (the reference's "
                          "commented-out MPI_Reduce, made real)")
    run.add_argument("--checkpoint-every", type=int)
    run.add_argument("--checkpoint-dir")
    run.add_argument("--async-io", dest="async_io",
                     choices=["on", "off", "auto"],
                     help="checkpoint/numerics I/O pipeline: on = "
                          "snapshot-and-continue (device-side copy at the "
                          "boundary; D2H + disk write in a background "
                          "writer, bounded queue), off = sync fallback "
                          "(device idles through fetch + write), auto "
                          "(default) = on")
    run.add_argument("--profile", dest="profile_dir", metavar="DIR",
                     help="write a jax.profiler trace of the solve to DIR")
    run.add_argument("--trace", metavar="FILE",
                     help="export the run's event timeline (chunk "
                          "dispatches, checkpoint snapshots, background-"
                          "writer D2H+publish spans) as Chrome trace-event "
                          "JSON viewable in Perfetto / chrome://tracing "
                          "(HEAT_TPU_TRACE=FILE is the env spelling; "
                          "HEAT_TPU_TRACE=off disables recording)")
    run.add_argument("--trace-buffer", dest="trace_buffer", type=int,
                     metavar="N",
                     help="event-ring capacity (default "
                          f"{trace_mod.DEFAULT_BUFFER}; 0 disables "
                          "recording)")
    run.add_argument("--check-numerics", action="store_true",
                     help="detect NaN/Inf per chunk (debug; forces syncs)")
    run.add_argument("--on-nan", dest="on_nan", choices=["abort", "rollback"],
                     help="non-finite response under --check-numerics: "
                          "abort (default) raises at the flagged step; "
                          "rollback restores the last verified-finite "
                          "boundary and re-steps (transient soft errors "
                          "recover; deterministic blow-ups still abort "
                          "after bounded retries)")
    run.add_argument("--inject", metavar="SPEC",
                     help="deterministic fault injection (chaos testing): "
                          "comma-separated 'kind[@step][:key=val]...' — "
                          "crash@N[:proc=P], nan@N, ckpt-corrupt@N, "
                          "ckpt-truncate@N, sink-error@N[:times=K], "
                          "sink-slow:ms=M; HEAT_TPU_FAULTS env var is "
                          "equivalent (faults fire only in incarnation 0 "
                          "unless :restart=R/-1 — a supervisor relaunch "
                          "does not re-fire them)")
    run.add_argument("--write-int", action=argparse.BooleanOptionalAction,
                     default=None,
                     help="dump the initial field to int.dat before solving "
                          "(single-process variant presets default on, like "
                          "the reference — fortran/serial/heat.f90:50-55; "
                          "--no-write-int opts out)")
    run.add_argument("--out", default="soln.dat", help="solution file path")
    run.add_argument("--soln", action="store_true",
                     help="force solution dump even if input.dat flag is 0")
    run.add_argument("--json", action="store_true",
                     help="also print a machine-readable result line")

    serve = sub.add_parser(
        "serve",
        help="multi-tenant serving engine: drain a JSONL file of solve "
             "requests as continuously-batched vmapped lanes (same-bucket "
             "requests step in one compiled program; finished lanes are "
             "swapped for queued requests without recompiling)")
    serve.add_argument("--requests", metavar="FILE.jsonl",
                       help="JSON Lines: one request object per line, keys "
                            "= HeatConfig physics fields (n, ntime, sigma, "
                            "nu, dom_len, ndim, dtype, ic, bc, bc_value) + "
                            "optional id, deadline_ms (wall budget from "
                            "submission), tenant, class "
                            "(interactive|standard|batch), and "
                            "until=steady with tol (retire at the first "
                            "chunk boundary whose residual EWMA passes "
                            "tol); '#' lines are "
                            "comments. Optional when --listen is given "
                            "(then it pre-loads the file before serving)")
    serve.add_argument("--listen", metavar="HOST:PORT",
                       help="run as a long-running online gateway instead "
                            "of a one-shot drain: POST /v1/solve admits "
                            "request lines into the running engine "
                            "(streamed records back), GET /metrics, "
                            "/healthz, POST /drainz for graceful drain. "
                            "Port 0 picks an ephemeral port (printed). "
                            "The process runs until /drainz completes "
                            "(or Ctrl-C, which drains)")
    serve.add_argument("--policy", choices=["fifo", "edf", "fair"],
                       default="fifo",
                       help="admission ordering (default fifo = submit "
                            "order, bit-identical to previous releases): "
                            "'edf' = SLO-class priority then earliest-"
                            "deadline-first within a class (deadline_ms "
                            "shapes who runs next, not just shedding); "
                            "'fair' = weighted fair share across tenants "
                            "(EDF within each tenant)")
    serve.add_argument("--tenant-weights", dest="tenant_weights",
                       metavar="NAME=W,...",
                       help="fair-share weights per tenant (policy=fair), "
                            "e.g. 'acme=4,free-tier=1'; unlisted tenants "
                            "weigh 1.0")
    serve.add_argument("--tenant-quota", dest="tenant_quota", type=int,
                       metavar="N",
                       help="per-tenant admission sub-quota: one tenant "
                            "may hold at most N queued requests (excess "
                            "gets a structured 'overloaded' rejection — "
                            "HTTP 429 — even when the global --max-queue "
                            "still has room)")
    serve.add_argument("--lanes", type=int, default=4,
                       help="max concurrent requests per bucket group "
                            "(default 4)")
    serve.add_argument("--chunk", type=int, default=16,
                       help="steps per device program call — the swap "
                            "granularity of continuous batching (default 16)")
    serve.add_argument("--buckets", default="256,512,1024",
                       help="comma-separated grid-side buckets; a request "
                            "is padded up to the smallest side that fits "
                            "(default 256,512,1024)")
    serve.add_argument("--mega-lanes", dest="mega_lanes", default="auto",
                       metavar="auto|N",
                       help="second placement tier: requests whose side "
                            "overflows every bucket run as sharded "
                            "mega-lanes — ONE request spanning the whole "
                            "device mesh (backends/sharded.py shard_map "
                            "advance) co-scheduled with the packed lanes "
                            "— instead of being rejected. N = concurrent "
                            "mega-lane slots; 'auto' (default) = 1 on a "
                            "multi-device host, 0 single-device; 0 "
                            "restores the bucket-overflow rejection "
                            "bit-identically")
    serve.add_argument("--dispatch-depth", default="on", metavar="on|off|N",
                       help="chunk programs kept in flight per bucket "
                            "group: the boundary D2H + bookkeeping of "
                            "chunk i overlap chunk i+1's compute instead "
                            "of fencing it. 'on' (default) = 2; N >= 1 "
                            "sets the depth explicitly; 'off' = fully "
                            "synchronous fallback for debugging (fence "
                            "every boundary, PR-3 behavior)")
    serve.add_argument("--out-dir", metavar="DIR",
                       help="write each result as DIR/<id>.npz (atomic "
                            "publish); default: results stay in memory")
    serve.add_argument("--serve-lane-kernel", dest="serve_lane_kernel",
                       choices=["auto", "pallas", "xla"], default="auto",
                       help="chunk-program body per bucket: 'auto' "
                            "(default) = the multi-lane Pallas kernels "
                            "on TPU wherever the bucket has a kernel "
                            "plan, the vmapped XLA stencil elsewhere; "
                            "'pallas'/'xla' force it. Both produce "
                            "bit-identical results (XLA is the oracle); "
                            "an unavailable Pallas bucket (f64, or a 3D "
                            "bucket no VMEM band fits) degrades to XLA "
                            "as a structured lane_kernel_fallback "
                            "record + counter, never an error")
    serve.add_argument("--serve-on-nan", dest="serve_on_nan",
                       choices=["fail", "rollback"], default="fail",
                       help="per-lane non-finite response (every chunk "
                            "boundary carries a device-computed isfinite "
                            "bit per lane): 'fail' (default) quarantines "
                            "the request — structured 'nonfinite' record, "
                            "lane freed, co-scheduled lanes untouched; "
                            "'rollback' restores that lane's last "
                            "verified-finite boundary snapshot and "
                            "re-steps it alone (transient poison recovers "
                            "bit-identically; deterministic blow-ups "
                            "quarantine after 2 retries)")
    serve.add_argument("--serve-deadline", dest="serve_deadline",
                       type=float, metavar="MS",
                       help="engine-default per-request wall budget in ms "
                            "from submission (a request's own deadline_ms "
                            "JSONL field overrides); an over-deadline "
                            "lane is preempted at its next chunk boundary "
                            "with status 'deadline', and queued requests "
                            "past their budget are shed without occupying "
                            "a lane (default: no deadline)")
    serve.add_argument("--max-queue", dest="max_queue", type=int,
                       metavar="N",
                       help="admission bound: submits beyond N queued "
                            "requests are shed with a structured "
                            "'overloaded' rejection instead of growing "
                            "the queue without bound (default: unbounded)")
    serve.add_argument("--fetch-watchdog", dest="fetch_watchdog",
                       type=float, metavar="SECONDS", default=600.0,
                       help="boundary-fetch watchdog: a chunk-boundary "
                            "D2H exceeding this fails that bucket "
                            "group's in-flight and queued requests "
                            "cleanly instead of hanging serve forever "
                            "(default 600; 0 = off)")
    serve.add_argument("--inject", metavar="SPEC",
                       help="engine-scoped deterministic fault injection "
                            "(runtime/faults.py grammar) incl. the "
                            "serve kinds: lane-nan@N[:req=ID] poisons a "
                            "lane's field once its request has run N "
                            "steps (no req= poisons every request); "
                            "fetch-hang[@N]:ms=M hangs the Nth boundary "
                            "fetch M ms (watchdog exercise). Per-request "
                            "specs ride each request's own 'inject' key")
    serve.add_argument("--trace", metavar="FILE",
                       help="export the engine's event ring as Chrome "
                            "trace-event JSON at drain (Perfetto / "
                            "chrome://tracing): per-lane occupancy "
                            "timelines, chunk pipelining, queue waits, "
                            "boundary fetches, writer publishes, with "
                            "flow arrows stitching each request's hops "
                            "across threads. HEAT_TPU_TRACE=FILE is the "
                            "env spelling; HEAT_TPU_TRACE=off disables "
                            "recording (including the flight recorder)")
    serve.add_argument("--trace-buffer", dest="trace_buffer", type=int,
                       metavar="N",
                       help="event-ring capacity (default "
                            f"{trace_mod.DEFAULT_BUFFER}). The ring is "
                            "the ALWAYS-ON flight recorder: even without "
                            "--trace, the last N events are dumped to "
                            "<out-dir>/flightrec-<ts>.trace.json when a "
                            "watchdog fires, a lane quarantines after "
                            "its rollback budget, or the scheduler loop "
                            "crashes; 0 disables recording entirely")
    serve.add_argument("--prof", default="on", metavar="on|off",
                       help="performance & cost observatory "
                            "(runtime/prof.py): online per-bucket chunk-"
                            "cost model, per-tenant usage ledger, memory "
                            "watermarks + leak sentinel, SLO burn-rate "
                            "monitor — all fed from timestamps the "
                            "scheduler already takes (overhead gate: "
                            "benchmarks/prof_overhead_lab.json). "
                            "'off' = A/B baseline (records keep their "
                            "usage stamps; aggregation off) (default on)")
    serve.add_argument("--slo-targets", dest="slo_targets",
                       metavar="CLASS=FRAC,...",
                       help="per-class SLO targets for the burn-rate "
                            "monitor, e.g. 'interactive=0.999,batch=0.8' "
                            "(deadline-hit fraction; error budget = "
                            "1 - target; defaults interactive=0.99, "
                            "standard=0.95, batch=0.9)")
    serve.add_argument("--mem-poll", dest="mem_poll", type=int,
                       metavar="N",
                       help="chunk boundaries between device-memory "
                            "watermark samples (leak sentinel; default "
                            "32, 0 = never sample)")
    serve.add_argument("--numerics", default="on", metavar="on|off",
                       help="numerics observatory (runtime/numerics.py): "
                            "per-lane residual EWMAs, discrete-maximum-"
                            "principle + heat-jump detectors, steady-"
                            "state records — fed from the four per-lane "
                            "stats the chunk programs fuse into the "
                            "boundary vector (no extra device passes or "
                            "transfers; overhead gate: benchmarks/"
                            "numerics_overhead_lab.json). 'off' = A/B "
                            "baseline (stats still ride the boundary; "
                            "host ingestion off) (default on)")
    serve.add_argument("--steady-tol", dest="steady_tol", type=float,
                       default=1e-12, metavar="TOL",
                       help="residual-EWMA threshold below which a lane "
                            "with steps remaining emits one steady_state "
                            "record (interior max|dT| per mini-step), and "
                            "— for until=steady requests without their "
                            "own tol — the default tolerance at which the "
                            "lane RETIRES early with exit=steady "
                            "(semantic scheduling; default 1e-12)")
    serve.add_argument("--numerics-guard", dest="numerics_guard",
                       choices=["warn", "quarantine"], default="warn",
                       help="what a numerics_violation does: 'warn' = "
                            "structured record + flight dump only; "
                            "'quarantine' = additionally fail the "
                            "request and free its lane (the PR-5 "
                            "nonfinite quarantine path — co-scheduled "
                            "lanes untouched) (default warn)")
    serve.add_argument("--probe-interval", dest="probe_interval",
                       type=float, default=0.0, metavar="S",
                       help="with --listen: submit a known-answer canary "
                            "probe (sine-eigenmode request under the "
                            "reserved '_probe' tenant, verified against "
                            "its closed-form decay) through the real "
                            "gateway every S seconds (serve/probe.py; "
                            "0 = prober off, the default)")
    serve.add_argument("--engine-ckpt-interval", dest="engine_ckpt_interval",
                       type=int, default=0, metavar="N",
                       help="engine-state checkpoint cadence: every N "
                            "processed chunk boundaries the scheduler "
                            "pauses dispatch at the next empty-pipeline "
                            "cut and snapshots the WHOLE engine — one "
                            "on-device copy per occupied lane (D2H on the "
                            "writer thread) plus a JSON manifest of lane "
                            "occupancy, queued requests, and usage "
                            "partials, written atomically with a "
                            "generation counter; a final checkpoint "
                            "always lands at drain. 0 = off (default)")
    serve.add_argument("--engine-ckpt-dir", dest="engine_ckpt_dir",
                       metavar="DIR",
                       help="where engine-state generations live "
                            "(default: <--out-dir>/engine-ckpt, else "
                            "./engine-ckpt)")
    serve.add_argument("--cache", default="off", choices=["on", "off"],
                       help="two-level solve cache (serve/solvecache.py): "
                            "a request whose canonical physics "
                            "fingerprint matches a finished result is "
                            "served from disk byte-identically without "
                            "occupying a lane (billed cached, zero "
                            "lane-seconds/steps); a match at a smaller "
                            "step count seeds the lane from the cached "
                            "frontier and steps only the delta "
                            "(steps_saved). Default off — off is "
                            "bit-identical to builds without the cache")
    serve.add_argument("--cache-dir", dest="cache_dir", metavar="DIR",
                       help="where cache entries live (default: "
                            "<--out-dir>/solve-cache, else ./solve-cache); "
                            "share one DIR across gateways to let the "
                            "fleet router serve fleet-wide hits at the "
                            "edge")
    serve.add_argument("--cache-max-bytes", dest="cache_max_bytes",
                       type=int, default=0, metavar="B",
                       help="LRU budget for the cache dir: after each "
                            "store, least-recently-hit entries are "
                            "evicted until total bytes <= B "
                            "(0 = unbounded, the default)")
    serve.add_argument("--resume", metavar="DIR",
                       help="crash-safe resume: before serving, rebuild "
                            "the engine from the newest valid engine "
                            "manifest in DIR — in-flight requests "
                            "continue at their last checkpointed boundary "
                            "(bit-identical to an uninterrupted run), "
                            "queued requests re-queue in policy order, "
                            "usage billing resumes from stamped partials; "
                            "a corrupt manifest is quarantined loudly and "
                            "discovery falls back one generation. "
                            "--requests rows whose ids the manifest "
                            "accounts for are skipped")
    serve.add_argument("--json", action="store_true",
                       help="also print a machine-readable summary line")

    fleet = sub.add_parser(
        "fleet",
        help="pod-scale fleet router: one stdlib-HTTP front end over N "
             "independent `heat-tpu serve --listen` gateways — edge "
             "admission, burn-aware least-loaded placement fed from "
             "each backend's GET /v1/status, fleet-wide /metrics + "
             "/statusz + /v1/usage, health probes with retry-on-"
             "alternate, and checkpoint-handoff work stealing "
             "(drain a loaded backend to its engine manifest, resume "
             "it on an idle one — bit-identical bytes across the "
             "migration)")
    fleet.add_argument("--backends", metavar="[NAME=]HOST:PORT,...",
                       help="comma-separated backend gateways (each a "
                            "`heat-tpu serve --listen` process); unnamed "
                            "entries get positional names b0,b1,...")
    fleet.add_argument("--backends-file", dest="backends_file",
                       metavar="FILE",
                       help="backend registry file: one [name=]host:port "
                            "per line, '#' comments; re-read when its "
                            "mtime changes, so new backends join the "
                            "fleet live (removing a line never evicts a "
                            "live backend)")
    fleet.add_argument("--listen", default="127.0.0.1:0",
                       metavar="HOST:PORT",
                       help="router bind address (default 127.0.0.1:0 = "
                            "ephemeral port, printed)")
    fleet.add_argument("--fleet-policy", dest="fleet_policy",
                       choices=["least-loaded", "round-robin"],
                       default="least-loaded",
                       help="placement policy: 'least-loaded' (default) "
                            "ranks by predicted backlog seconds (cost "
                            "model x queue work) with burn-aware "
                            "demotion and mega-capability routing; "
                            "'round-robin' is the A/B baseline")
    fleet.add_argument("--health-interval", dest="health_interval",
                       type=float, default=2.0, metavar="S",
                       help="health-probe cadence: GET /healthz + "
                            "/v1/status per backend every S seconds "
                            "(default 2)")
    fleet.add_argument("--steal-threshold", dest="steal_threshold",
                       type=float, default=0.0, metavar="S",
                       help="work-stealing imbalance threshold in "
                            "predicted-backlog seconds: when "
                            "max-min exceeds S and the victim has "
                            "queued work, the router drains the victim "
                            "to a checkpoint (/drainz?handoff=1) and "
                            "resumes its manifest on the idlest backend "
                            "(default 0 = automatic stealing off)")
    fleet.add_argument("--steal-cooldown", dest="steal_cooldown",
                       type=float, default=10.0, metavar="S",
                       help="minimum seconds between automatic steals "
                            "(thrash guard; default 10)")
    fleet.add_argument("--cache-dir", dest="fleet_cache_dir",
                       metavar="DIR",
                       help="shared solve-cache dir (point it at the "
                            "same --cache-dir the backends publish "
                            "into): the router consults it read-only "
                            "before placement — a fleet-wide full hit "
                            "is served at the edge without touching any "
                            "backend, a prefix hit steers placement to "
                            "a cache-enabled backend")
    fleet.add_argument("--ckpt-root", dest="ckpt_root", metavar="DIR",
                       help="fallback checkpoint root: backend NAME's "
                            "engine manifests under DIR/NAME when its "
                            "status payload names no checkpoint dir "
                            "(default: trust each backend's "
                            "--engine-ckpt-dir as reported)")
    fleet.add_argument("--inject", metavar="SPEC",
                       help="fleet-scoped deterministic fault injection "
                            "(runtime/faults.py grammar): "
                            "backend-down@N[:backend=K] drops the TCP "
                            "target at the Nth forwarded request "
                            "(K names a backend; default = whichever "
                            "was chosen); backend-slow:ms=M sleeps "
                            "every forward M ms; "
                            "backend-flap:period=MS[:backend=K] square-"
                            "waves the target down/up per half-period; "
                            "stream-cut@N[:backend=K] breaks the relay "
                            "stream after N records while the backend "
                            "stays alive; "
                            "backend-partition[:ms=M][:backend=K] makes "
                            "every connect hang M ms then time out")
    fleet.add_argument("--breaker-trip", dest="breaker_trip", type=int,
                       default=3, metavar="N",
                       help="consecutive relay/probe errors that open a "
                            "backend's circuit breaker (default 3); an "
                            "open breaker excludes the backend from "
                            "placement and stealing until the sine "
                            "canary passes through the router path")
    fleet.add_argument("--breaker-cooldown", dest="breaker_cooldown",
                       type=float, default=5.0, metavar="S",
                       help="seconds an open breaker waits before its "
                            "half-open canary (default 5; doubles on "
                            "every failed canary, capped at 120)")
    fleet.add_argument("--retry-budget", dest="retry_budget",
                       type=float, default=20.0, metavar="TOKENS",
                       help="fleet-wide retry token bucket size "
                            "(default 20): each batch re-placement "
                            "spends one token, each delivered success "
                            "refills 0.2 — a dry bucket sheds instead "
                            "of amplifying overload")
    fleet.add_argument("--hedge-factor", dest="hedge_factor",
                       type=float, default=0.0, metavar="F",
                       help="tail-latency hedging for the interactive "
                            "class: duplicate a row onto a second "
                            "breaker-closed backend once it has waited "
                            "F x its predicted service time (+0.75s "
                            "floor); first terminal record wins, the "
                            "loser is cancelled at its next chunk "
                            "boundary (default 0 = off)")
    fleet.add_argument("--trace", metavar="FILE",
                       help="export the ROUTER's event ring at drain: "
                            "forward spans + synthesized backend solve "
                            "spans per backend track — one fleet "
                            "timeline (also GET /tracez live)")
    fleet.add_argument("--trace-buffer", dest="trace_buffer", type=int,
                       metavar="N",
                       help="router event-ring capacity (default "
                            f"{trace_mod.DEFAULT_BUFFER}); the ring is "
                            "flight-dumped on backend loss; 0 disables")
    fleet.add_argument("--json", action="store_true",
                       help="also print a machine-readable summary line")

    usage = sub.add_parser(
        "usage",
        help="per-tenant usage ledger: render lane-seconds / steps / "
             "chunks / bytes-written per tenant and SLO class, from a "
             "running gateway (GET /v1/usage) or from a saved stream of "
             "serve_request JSON records")
    usage.add_argument("source",
                       help="gateway base URL (http://HOST:PORT — "
                            "/v1/usage is fetched) or a file of "
                            "serve_request JSON lines (the offline "
                            "drain's stdout records)")
    usage.add_argument("--json", action="store_true",
                       help="print the raw ledger JSON instead of the "
                            "table")

    pc = sub.add_parser(
        "perfcheck",
        help="performance regression gate: run the observatory-overhead "
             "lab (benchmarks/prof_overhead_lab.py), compare it against "
             "the committed baseline JSON within a tolerance band, "
             "re-validate every committed lab's internal gates, and "
             "cross-check the online cost model against "
             "calibration_v5e.json")
    pc.add_argument("--fresh", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="run a fresh prof_overhead_lab and compare it "
                         "to the committed baseline (--no-fresh = only "
                         "re-validate committed artifacts; fast)")
    pc.add_argument("--tolerance", type=float, default=0.5,
                    help="relative band for fresh-vs-baseline throughput "
                         "(default 0.5 = within 50%% either way — CI "
                         "boxes jitter; the hard gates are the labs' "
                         "internal ones)")
    pc.add_argument("--baseline",
                    help="baseline prof_overhead_lab JSON (default: the "
                         "committed benchmarks/prof_overhead_lab.json)")

    chk = sub.add_parser(
        "check",
        help="invariant guard: run the project-native static-analysis "
             "suite (heat_tpu/analysis) over the package source — "
             "hot-path purity, lock discipline, traced-code determinism, "
             "Mosaic kernel safety, record-schema drift. Exit 0 = clean; "
             "pure AST, no device, runs in seconds")
    chk.add_argument("--rules", metavar="LIST",
                     help="comma-separated rule families to run "
                          "(default: all; see --list-rules)")
    chk.add_argument("--list-rules", action="store_true",
                     help="print the rule-family table and exit")
    chk.add_argument("--update-schemas", action="store_true",
                     help="regenerate analysis/schemas/records.json from "
                          "the current source instead of gating against "
                          "it — the intentional-schema-drift workflow: "
                          "commit the registry diff with the code change "
                          "so consumers see the schema change reviewed")
    chk.add_argument("--root", metavar="DIR",
                     help="package root to analyze (default: the "
                          "installed heat_tpu package directory)")
    chk.add_argument("--json", action="store_true",
                     help="machine-readable results (one JSON object: "
                          "stats + violations)")
    chk.add_argument("--strict-allows", action="store_true",
                     help="fail on stale allow markers (markers whose "
                          "rule no longer fires at that site, or whose "
                          "rule id is unknown). Default: warn only — a "
                          "stale marker silently pre-authorizes a future "
                          "regression, but fixing it is a separate diff")
    chk.add_argument("--dead-code", action="store_true",
                     help="informational: list public package functions "
                          "unreachable from any entry point (tests, "
                          "benchmarks, module-level code, decorated "
                          "defs) and exit 0 — the closure is "
                          "conservative, so every listed function "
                          "really is unreferenced")

    aud = sub.add_parser(
        "audit",
        help="program auditor: trace every registered program family "
             "(solo step, lane advance/loader, sharded mega) to jaxprs "
             "and AOT-lowered StableHLO on abstract inputs — no "
             "execution, no chip — and machine-check donation, traced "
             "purity, dtype discipline, the compile-key budget, and "
             "drift-gated program digests. Exit 0 = all contracts hold")
    aud.add_argument("--update-digests", action="store_true",
                     help="regenerate analysis/digests/programs.json "
                          "from the current source instead of gating "
                          "against it — the intentional-drift workflow: "
                          "commit the registry diff with the code change "
                          "so the program change is reviewed")
    aud.add_argument("--contracts", metavar="LIST",
                     help="comma-separated contract families to check "
                          "(default: all; see --list-contracts)")
    aud.add_argument("--fast", action="store_true",
                     help="skip the per-program cost/roofline extraction "
                          "detail and run only the cheap contracts "
                          "(digest, donation, purity, budget) — the "
                          "make-check tier; full audits run in "
                          "benchmarks/extras")
    aud.add_argument("--list-contracts", action="store_true",
                     help="print the contract-family table and exit")
    aud.add_argument("--registry", metavar="FILE",
                     help="digest registry path (default: the committed "
                          "heat_tpu/analysis/digests/programs.json)")
    aud.add_argument("--json", action="store_true",
                     help="machine-readable report (one JSON object: "
                          "families, budget, digests, violations)")

    trc = sub.add_parser(
        "trace",
        help="render a text timeline summary from a trace file (a "
             "--trace export, a flightrec-*.trace.json dump, or a saved "
             "GET /tracez response): per-lane utilization, top "
             "queue-wait requests, boundary-fetch/device-idle totals")
    trc.add_argument("tracefile", help="Chrome trace-event JSON file")
    trc.add_argument("--top", type=int, default=5,
                     help="how many top queue-wait requests to list "
                          "(default 5)")

    viz = sub.add_parser("viz", help="render a .dat file as a 3D surface")
    viz.add_argument("datfile")
    viz.add_argument("--save", default="sol.png")
    viz.add_argument("--ndim", type=int, choices=[2, 3], default=2,
                     help="3: render the mid-plane slice of an x-y-z-T file")

    info = sub.add_parser("info", help="show devices / native-lib status")  # noqa: F841

    plan = sub.add_parser(
        "plan", help="explain what the framework would run for a config: "
                     "kernel choice, tile/halo geometry, mesh, halo traffic")
    plan.add_argument("--input", default="input.dat")
    plan.add_argument("--variant", choices=sorted(VARIANTS))
    plan.add_argument("--backend", choices=["serial", "xla", "pallas", "sharded"])
    plan.add_argument("--dtype", choices=["float64", "float32", "bfloat16"])
    plan.add_argument("--ndim", type=int, choices=[2, 3])
    plan.add_argument("--mesh", type=_parse_mesh)
    plan.add_argument("--fuse-steps", type=int)
    plan.add_argument("--local-kernel", choices=["auto", "xla", "pallas"])
    plan.add_argument("--ic", choices=["hat", "hat_half", "hat_small",
                                       "uniform", "zero"])
    plan.add_argument("--bc", choices=["edges", "ghost", "periodic"])
    plan.add_argument("--comm", choices=["direct", "staged"])

    bench = sub.add_parser(
        "bench",
        help="headline throughput benchmark (grid-points/sec/chip, f32 "
             "Pallas stencil) — the reference's python/cuda benchmark "
             "workflow as one command; prints a human summary + the same "
             "JSON record as bench.py")
    bench.add_argument("--n", type=int, default=0,
                       help="grid side (default 4096 on TPU, 512 elsewhere)")
    bench.add_argument("--steps", type=int, default=0,
                       help="timesteps per timed call (default 8192 TPU, "
                            "256 elsewhere)")
    bench.add_argument("--repeats", type=int, default=3)

    cal = sub.add_parser(
        "calibrate",
        help="fit this chip's planner constants (HBM stream + 2D/3D "
             "stencil sweeps, minutes on a real chip) and write a "
             "ChipModel JSON consumable via HEAT_CHIP_CALIBRATION — "
             "turns the spec-proxy tables for a newly attached chip "
             "class into measured numbers")
    cal.add_argument("--out", default="calibration.json")
    cal.add_argument("--quick", action="store_true",
                     help="tiny shapes (harness check; rates not "
                          "representative even on a real chip)")

    launch = sub.add_parser(
        "launch",
        help="run N distributed processes on this machine (the reference's "
             "'mpirun -np N' — fortran/mpi+cuda/makefile:1-2). On a real "
             "pod the scheduler starts one process per host instead; this "
             "is the single-node development launcher.")
    launch.add_argument("-n", "--processes", type=int, default=2)
    launch.add_argument("--devices-per-process", type=int, default=1,
                        help="virtual CPU devices contributed per process")
    launch.add_argument("--max-restarts", type=int, default=1, metavar="K",
                        help="self-healing supervisor: after a mid-run "
                             "worker death, stop the surviving world, "
                             "validate/quarantine checkpoints, and relaunch "
                             "with resume up to K times under exponential "
                             "backoff (default 1; 0 disables). Startup-class "
                             "failures (<30s, no checkpoint yet) get one "
                             "extra clean retry outside this budget")
    launch.add_argument("--deadline", type=int, metavar="S", default=None,
                        help="per-attempt wall-clock limit in seconds; the "
                             "flag wins over HEAT_TPU_LAUNCH_TIMEOUT_S "
                             "(default 3600). A deadline exit is rc=124 and "
                             "is never restarted — it is a budget, not a "
                             "fault")
    launch.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="heat-tpu arguments, e.g.: run --backend sharded")
    return p


def _apply_overrides(cfg: HeatConfig, args) -> HeatConfig:
    """Fold CLI flags into the config. getattr-safe throughout so any
    subcommand exposing a subset of run's flags (``plan``) reuses this
    instead of hand-rolling a drifting copy."""
    over = {}
    for field in ("backend", "dtype", "ic", "bc", "ndim", "comm", "exchange",
                  "fuse_steps", "local_kernel", "heartbeat_every",
                  "checkpoint_every", "checkpoint_dir", "async_io",
                  "profile_dir", "write_int", "on_nan", "inject"):
        v = getattr(args, field, None)
        if v is not None:
            over[field] = v
    if getattr(args, "bc_value", None) is not None:
        over["bc_value"] = args.bc_value
    if getattr(args, "mesh", None) is not None:
        over["mesh_shape"] = args.mesh
    for flag in ("report_sum", "check_numerics", "soln", "parity_order"):
        if getattr(args, flag, False):
            over[flag] = True
    return cfg.with_(**over)


def _warn_if_unstable(cfg: HeatConfig) -> None:
    """Loud (master-gated) warning when sigma exceeds the explicit FTCS
    stability bound 1/(2*ndim) — a warning, not an error: the reference
    admits such configs (its serial input.dat sigma=0.25 is exactly AT the
    2D bound, and nothing stops --ndim 3 from pushing the same sigma past
    1/6; FTCS derivation at fortran/serial/heat.f90:15-17). The framework
    can say so before the user burns a run into NaNs."""
    from .models import get_model

    model = get_model(cfg)
    if not model.is_stable(cfg):
        lim = model.stability_limit()
        master_print(
            f"WARNING: sigma={cfg.sigma:g} exceeds the explicit FTCS "
            f"stability bound 1/(2*ndim)={lim:g} for ndim={cfg.ndim} — "
            f"the update can diverge to NaN/Inf; lower sigma (or run with "
            f"--check-numerics to catch the blow-up at its first step)")


def cmd_run(args) -> int:
    path = Path(args.input)
    if not path.exists():
        print(f"error: {path} not found (expected 'n sigma nu dom_len ntime [soln]')",
              file=sys.stderr)
        return 2
    cfg = parse_input(path)
    if args.variant:
        cfg = variant_config(args.variant, cfg)
    cfg = _apply_overrides(cfg, args)

    try:
        trace_path, trace_cap = trace_mod.resolve_trace(args.trace,
                                                        args.trace_buffer)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    tracer = trace_mod.configure(capacity=trace_cap)

    if args.virtual_devices:
        # must land before the first backend touch; a plain JAX_PLATFORMS
        # env var is not enough where a site hook pins the TPU platform
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.virtual_devices}")
        import jax

        jax.config.update("jax_platforms", "cpu")

    if cfg.backend == "sharded":
        # join the multi-process world before any backend/device use — the
        # first act of the reference's distributed variants (mpi_init +
        # rank->GPU binding, fortran/mpi+cuda/heat.F90:60-70). Single-host
        # runs: a cheap no-op.
        from .parallel.dist import init_distributed

        init_distributed()

    _warn_if_unstable(cfg)

    axes = coords(cfg)
    if cfg.write_int:
        from .io import write_int_dat

        write_int_dat("int.dat", axes, initial_condition(cfg))

    from .backends import solve  # deferred: import cost only when running

    res = solve(cfg)
    for line in res.timing.report_lines():
        master_print(line)
    if trace_path:
        tracer.export(trace_path)
        master_print(f"wrote trace {trace_path} (open in Perfetto / "
                     f"chrome://tracing; summary: heat-tpu trace "
                     f"{trace_path})")
    if res.gsum is not None:
        master_print(f"Sum of Temperature: {res.gsum:.10g}")

    if cfg.soln:
        from .io import write_soln, write_soln_blocks, write_soln_sharded

        outdir = Path(args.out).parent or "."
        if res.T is None:
            # multi-host: the global field spans other processes — every
            # process writes its own addressable shards, the reference's
            # per-rank soln#####.dat contract (mpi+cuda/heat.F90:277-288)
            if res.T_dev is not None and res.mesh is not None:
                files = write_soln_sharded(outdir, axes, res.T_dev, res.mesh)
                print(f"[process {_process_index()}] wrote "
                      f"{len(files)} shard files "
                      f"({files[0].name} .. {files[-1].name})")
            else:
                master_print("solution dump skipped: field was not fetched")
        else:
            if res.mesh_shape and any(s > 1 for s in res.mesh_shape):
                # per-shard files, reference per-rank contract
                files = write_soln_blocks(outdir, axes, res.T, res.mesh_shape)
                master_print(f"wrote {len(files)} per-shard files "
                             f"({files[0].name} .. {files[-1].name})")
            write_soln(args.out, axes, res.T)
            master_print(f"wrote {args.out}")

    if args.json:
        rec = {
            "n": cfg.n, "ndim": cfg.ndim, "ntime": cfg.ntime,
            "backend": cfg.backend, "dtype": cfg.dtype,
            "solve_s": res.timing.solve_s,
            "per_step_s": res.timing.per_step_s,
            "points_per_s": res.timing.points_per_s,
            "gsum": res.gsum,
            "gsum_dtype": res.gsum_dtype,
        }
        if res.timing.overlap_s is not None:
            # async pipeline ran: how much I/O wall time compute hid, and
            # what the driver still paid (backpressure + final drain)
            rec["overlap_s"] = res.timing.overlap_s
            rec["io_wait_s"] = res.timing.io_wait_s
        if res.guard is not None:
            # the row must say when it measured the DEGRADED program (and
            # what the probe cost / what became of the orphan compile)
            rec["guard"] = dataclasses.asdict(res.guard)
        master_print(json.dumps(rec))
    return 0


def _process_index() -> int:
    import jax

    return jax.process_index()


def _serve_report(summary, ok: int, args) -> None:
    """The shared end-of-serve report (offline drain + drained gateway)."""
    import json as _json

    failed = summary["requests"] - ok - summary.get("rejected", 0)
    master_print(f"served {summary['requests']} request(s): {ok} ok, "
                 f"{summary.get('rejected', 0)} rejected, "
                 f"{failed} failed "
                 f"({summary['step_compiles']} stepping + "
                 f"{summary['tail_compiles']} tail compile(s), "
                 f"{summary['compile_s']:.3f}s compiling)")
    pl = summary.get("placement") or {}
    if pl.get("mega") or summary.get("mega_compiles"):
        master_print(f"placement: {pl.get('packed', 0)} packed, "
                     f"{pl.get('mega', 0)} mega (mesh-spanning sharded "
                     f"lanes; {summary.get('mega_lanes', 0)} slot(s), "
                     f"{summary.get('mega_compiles', 0)} mega compile(s))")
    master_print(f"dispatch: depth {summary['dispatch_depth']}, "
                 f"policy {summary['policy']}, "
                 f"lane kernel {summary.get('lane_kernel', 'auto')}"
                 + (f" ({summary['lane_kernel_fallbacks']} bucket tier(s) "
                    f"fell back to XLA)"
                    if summary.get("lane_kernel_fallbacks") else "")
                 + f", {summary['chunks_dispatched']} chunk(s) "
                 f"({summary['tail_chunks']} tail), "
                 f"{summary['boundary_waits']} boundary wait(s) totaling "
                 f"{summary['boundary_wait_s']:.3f}s, "
                 f"est. device idle {summary['device_idle_s']:.3f}s")
    faultful = any(summary[k] for k in ("lanes_quarantined", "rollbacks",
                                        "deadline_misses", "shed",
                                        "watchdog_fired"))
    if faultful:
        master_print(f"fault domains: "
                     f"{summary['lanes_quarantined']} quarantined, "
                     f"{summary['rollbacks']} rollback(s), "
                     f"{summary['deadline_misses']} deadline miss(es), "
                     f"{summary['shed']} shed, "
                     f"{summary['watchdog_fired']} watchdog timeout(s)")
    if summary.get("numerics"):
        probes = ("" if "probe_pass" not in summary else
                  f"; probes {summary['probe_pass']} pass / "
                  f"{summary['probe_fail']} fail")
        master_print(f"numerics: {summary.get('steady_lanes', 0)} steady "
                     f"lane(s), {summary.get('numerics_violations', 0)} "
                     f"violation(s) (guard "
                     f"{summary.get('numerics_guard', 'warn')})"
                     + probes)
    if summary.get("steady_exits"):
        master_print(f"semantic scheduling: {summary['steady_exits']} "
                     f"steady exit(s), {summary.get('steps_saved', 0)} "
                     f"step(s) saved")
    cache = summary.get("cache")
    if cache:
        master_print(f"solve cache: {cache['hits_full']} full hit(s), "
                     f"{cache['hits_prefix']} prefix hit(s), "
                     f"{cache['misses']} miss(es), "
                     f"{cache['entries']} entr(ies) / "
                     f"{cache['bytes'] / 2**20:.2f} MiB on disk, "
                     f"{cache['evictions']} evicted, "
                     f"{cache['quarantined']} quarantined "
                     f"({cache['dir']})")
    cm = summary.get("cost_model") or []
    if cm:
        tops = sorted(cm, key=lambda e: -e["wall_s"])[:3]
        more = f" (+{len(cm) - 3} more)" if len(cm) > 3 else ""
        master_print("cost model: " + "; ".join(
            f"{e['bucket']} xL{e['lanes']} d{e['depth']} "
            f"[{e.get('kernel', 'xla')}/{e.get('placement', 'packed')}]: "
            f"{e['ewma_s_per_lane_step'] or 0:.3e} s/lane-step "
            f"({e['chunks']} chunks)" for e in tops) + more)
    mem = summary.get("mem") or {}
    if mem.get("samples"):
        master_print(f"observatory: mem peak "
                     f"{(mem.get('peak_bytes') or 0) / 2**20:.1f} MiB "
                     f"({mem['source']}, {mem['samples']} sample(s), "
                     f"{mem['warnings']} leak warning(s)); "
                     f"{summary.get('flightrec_dumps', 0)} flight dump(s)")
    if args.json:
        master_print(_json.dumps(summary, sort_keys=True))


def cmd_serve(args) -> int:
    """Drain a JSONL request file through the batched serving engine —
    or, with ``--listen``, run the long-lived online gateway over it.

    Offline: per-request structured records stream as JSON lines while
    lanes finish; the exit code is 0 only when every request served
    cleanly (a rejected or failed request is that request's record AND a
    nonzero exit, so batch drivers notice without parsing records).
    Online: the process serves HTTP until ``POST /drainz`` completes (or
    Ctrl-C, which triggers the same graceful drain), then prints the
    same summary over everything it served.
    """
    from .config import parse_dispatch_depth, parse_listen, \
        parse_mega_lanes, parse_on_off, parse_slo_targets, \
        parse_tenant_weights
    from .serve import Engine, ServeConfig, serve_requests

    path = None
    if args.requests is not None:
        path = Path(args.requests)
        if not path.exists():
            print(f"error: {path} not found", file=sys.stderr)
            return 2
    elif args.listen is None and args.resume is None:
        print("error: need --requests FILE.jsonl, --listen HOST:PORT, "
              "--resume DIR, or a combination", file=sys.stderr)
        return 2
    try:
        buckets = tuple(int(b) for b in str(args.buckets).split(",") if b)
        listen = parse_listen(args.listen) if args.listen else None
        trace_path, trace_cap = trace_mod.resolve_trace(args.trace,
                                                        args.trace_buffer)
        scfg = ServeConfig(lanes=args.lanes, chunk=args.chunk,
                           buckets=buckets, out_dir=args.out_dir,
                           dispatch_depth=parse_dispatch_depth(
                               args.dispatch_depth),
                           on_nan=args.serve_on_nan,
                           lane_kernel=args.serve_lane_kernel,
                           mega_lanes=parse_mega_lanes(args.mega_lanes),
                           deadline_ms=args.serve_deadline,
                           max_queue=args.max_queue,
                           fetch_timeout_s=(args.fetch_watchdog
                                            if args.fetch_watchdog else None),
                           inject=args.inject or "",
                           policy=args.policy,
                           tenant_weights=parse_tenant_weights(
                               args.tenant_weights or ""),
                           tenant_quota=args.tenant_quota,
                           trace=trace_path, trace_buffer=trace_cap,
                           prof=parse_on_off(args.prof, "--prof"),
                           slo_targets=parse_slo_targets(
                               args.slo_targets or ""),
                           numerics=parse_on_off(args.numerics,
                                                 "--numerics"),
                           steady_tol=args.steady_tol,
                           numerics_guard=args.numerics_guard,
                           engine_ckpt_interval=args.engine_ckpt_interval,
                           engine_ckpt_dir=args.engine_ckpt_dir,
                           cache=parse_on_off(args.cache, "--cache"),
                           cache_dir=args.cache_dir,
                           cache_max_bytes=args.cache_max_bytes,
                           **({"mem_poll_every": args.mem_poll}
                              if args.mem_poll is not None else {}))
        if args.probe_interval < 0:
            raise ValueError(f"--probe-interval must be >= 0, got "
                             f"{args.probe_interval}")
        if args.probe_interval and args.listen is None:
            raise ValueError("--probe-interval needs --listen (the "
                             "prober probes the HTTP gateway)")
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    eng = None
    skip_ids = set()
    if args.resume is not None:
        # resume BEFORE any file rows or HTTP traffic: the manifest is
        # the authority on every request it accounts for (including
        # mid-solve progress); later submits only add NEW work
        from .serve.resume import resume_engine

        eng = Engine(scfg)
        try:
            skip_ids = resume_engine(eng, args.resume)
        except (ValueError, OSError) as e:
            print(f"error: --resume {args.resume} failed: {e}",
                  file=sys.stderr)
            return 2

    if listen is None:
        if path is not None:
            records, summary = serve_requests(path, scfg, engine=eng,
                                              skip_ids=skip_ids)
        else:
            records = eng.results()
            summary = eng.summary()
        ok = sum(1 for r in records if r["status"] == "ok")
        _serve_report(summary, ok, args)
        if scfg.trace:
            master_print(f"wrote trace {scfg.trace} (open in Perfetto / "
                         f"chrome://tracing; summary: heat-tpu trace "
                         f"{scfg.trace})")
        return 0 if ok == summary["requests"] else 1

    # --- online gateway mode ---------------------------------------------
    from .serve import Gateway, load_requests, submit_parsed

    eng = eng if eng is not None else Engine(scfg)
    parse_failures = 0
    if path is not None:
        for row in load_requests(path):
            if row.id is not None and row.id in skip_ids:
                continue   # recovered (or finished) by --resume
            if row.cfg is None:
                parse_failures += 1
                master_print(f"serve: rejected request line: {row.error}")
            else:
                submit_parsed(eng, row)
    gw = Gateway(eng, listen[0], listen[1]).start()
    master_print(f"gateway listening on http://{gw.address} — "
                 f"POST /v1/solve (NDJSON), GET /v1/requests/<id>, "
                 f"/healthz, /metrics; POST /drainz to drain "
                 f"(policy {scfg.policy})")
    prober = None
    if args.probe_interval:
        from .serve.probe import Prober

        prober = Prober(f"http://{gw.address}",
                        interval_s=args.probe_interval).start()
        eng.prober = prober   # /metrics + /statusz read stats() here
        master_print(f"prober armed: sine-eigenmode canary every "
                     f"{args.probe_interval:g}s through the real "
                     f"gateway path (tenant '_probe' — probe_result "
                     f"records; /metrics heat_tpu_probe_*)")
    try:
        gw.wait_drained()
    except KeyboardInterrupt:
        master_print("gateway: interrupt — draining (in-flight lanes "
                     "finish; Ctrl-C again to abandon)")
        gw.request_drain()
        gw.wait_drained()
    if prober is not None:
        prober.stop()
        ps = prober.stats()
        # fold the probe verdicts into the end-of-serve summary so the
        # drained report (and --json consumers) carry them
        probe_counts = {"probe_pass": ps["passes"],
                        "probe_fail": ps["fails"]}
    else:
        probe_counts = {}
    summary = eng.summary()
    summary.update(probe_counts)
    summary["requests"] += parse_failures
    if parse_failures:
        summary["rejected"] = summary.get("rejected", 0) + parse_failures
    ok = summary.get("ok", 0)
    _serve_report(summary, ok, args)
    if scfg.trace:
        master_print(f"wrote trace {scfg.trace} (open in Perfetto / "
                     f"chrome://tracing; summary: heat-tpu trace "
                     f"{scfg.trace})")
    gw.close()
    if eng.loop_error is not None:
        print(f"error: scheduler loop failed: {eng.loop_error}",
              file=sys.stderr)
        return 1
    return 0 if ok == summary["requests"] else 1


def cmd_fleet(args) -> int:
    """Run the fleet router (heat_tpu/fleet) until drained: the pod-
    scale front end over N ``heat-tpu serve --listen`` backends. The
    router itself never touches a device — it is pure stdlib HTTP +
    placement math, so it runs happily on the smallest host in the
    pod."""
    import time

    from .config import parse_listen
    from .fleet.registry import BackendRegistry, parse_backends
    from .fleet.router import FleetConfig, Router

    if not args.backends and not args.backends_file:
        print("error: need --backends HOST:PORT,... and/or "
              "--backends-file FILE", file=sys.stderr)
        return 2
    try:
        listen = parse_listen(args.listen)
        backends = parse_backends(args.backends) if args.backends else []
        trace_path, trace_cap = trace_mod.resolve_trace(args.trace,
                                                        args.trace_buffer)
        fcfg = FleetConfig(policy=args.fleet_policy,
                           health_interval_s=args.health_interval,
                           steal_threshold_s=args.steal_threshold,
                           steal_cooldown_s=args.steal_cooldown,
                           ckpt_root=args.ckpt_root,
                           cache_dir=args.fleet_cache_dir,
                           inject=args.inject or "",
                           breaker_trip=args.breaker_trip,
                           breaker_cooldown_s=args.breaker_cooldown,
                           retry_budget_cap=args.retry_budget,
                           hedge_factor=args.hedge_factor,
                           trace_buffer=trace_cap)
        registry = BackendRegistry(backends,
                                   backends_file=args.backends_file)
        if not registry.snapshot():
            raise ValueError("no backends: the --backends flag and the "
                             "--backends-file are both empty")
        rt = Router(registry, listen[0], listen[1], fcfg).start()
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    names = ", ".join(f"{b.name}={b.address}" for b in registry.snapshot())
    master_print(f"fleet router listening on http://{rt.address} — "
                 f"POST /v1/solve routes across [{names}] "
                 f"(policy {fcfg.policy}, steal threshold "
                 f"{fcfg.steal_threshold_s or 'off'}); GET /metrics "
                 f"/statusz /v1/status /v1/usage /tracez; POST /drainz "
                 f"stops admission")
    try:
        while not rt.draining:
            time.sleep(0.25)
        # admission stopped: let in-flight streams finish
        deadline = time.monotonic() + fcfg.stream_timeout_s
        while rt.pending_count() and time.monotonic() < deadline:
            time.sleep(0.25)
    except KeyboardInterrupt:
        master_print("fleet: interrupt — admission stopped (backends "
                     "keep their in-flight work; drain them "
                     "individually)")
        rt.request_drain()
    snap = rt.snapshot()
    if trace_path:
        rt.tracer.export(trace_path)
        master_print(f"wrote trace {trace_path} (open in Perfetto; "
                     f"summary: heat-tpu trace {trace_path})")
    r = snap["router"]
    master_print(f"fleet: drained — {r['requests']} routed, "
                 f"{r['edge_rejected']} rejected at the edge, "
                 f"{r['retries']} batch retries, {len(r['steals'])} "
                 f"steal(s), {r['lost']} backend(s) lost")
    if snap.get("cache") is not None:
        master_print(f"fleet: solve cache — {r['cache_edge_hits']} edge "
                     f"hit(s), {r['cache_prefix_hints']} prefix "
                     f"placement hint(s)")
    hd = r["hedges"]
    if (r["deadline_shed"] or r["brownout_shed"] or r["stream_cuts"]
            or hd["fired"] or r["retry_budget"]["denied"]):
        master_print(f"fleet: resilience — {r['deadline_shed']} "
                     f"deadline-shed, {r['brownout_shed']} brownout-"
                     f"shed, {r['stream_cuts']} stream cut(s) "
                     f"re-driven, {hd['fired']} hedge(s) fired "
                     f"({hd['won']} won, {hd['cancelled']} cancelled), "
                     f"{r['retry_budget']['denied']} retr(ies) denied "
                     f"by the budget")
    if args.json:
        print(json.dumps({"event": "fleet_summary", **r}, sort_keys=True))
    rt.close()
    return 0


def cmd_usage(args) -> int:
    """Render the per-tenant usage ledger as a table (or raw JSON) from
    either a running gateway's ``GET /v1/usage`` or a saved stream of
    ``serve_request`` JSON records — the offline spelling re-aggregates
    the exact per-record usage stamps, so both sources reconcile with
    each other by construction (runtime/prof.py UsageLedger)."""
    import json as _json

    src = str(args.source)
    if src.startswith(("http://", "https://")):
        import urllib.request

        url = src.rstrip("/")
        if not url.endswith("/v1/usage"):
            url += "/v1/usage"
        try:
            with urllib.request.urlopen(url, timeout=30) as resp:
                payload = _json.loads(resp.read().decode())
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print(f"error: GET {url} failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            return 2
    else:
        path = Path(src)
        if not path.exists():
            print(f"error: {src} is neither an http(s) URL nor a file",
                  file=sys.stderr)
            return 2
        from .runtime.prof import UsageLedger, empty_usage

        ledger = UsageLedger()
        found = 0
        for line in path.read_text().splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue   # records interleave with human report lines
            try:
                d = _json.loads(line)
            except ValueError:
                continue
            if d.get("event") != "serve_request":
                continue
            found += 1
            ledger.add(d.get("tenant") or "default",
                       d.get("class") or "standard",
                       d.get("status") or "?",
                       d.get("usage") or empty_usage(),
                       placement=d.get("placement"))
        if not found:
            print(f"error: no serve_request JSON records found in {src}",
                  file=sys.stderr)
            return 2
        payload = ledger.snapshot()
    if args.json:
        print(_json.dumps(payload, sort_keys=True))
        return 0
    hdr = (f"{'tenant':<20} {'class':<12} {'requests':>8} {'lane_s':>10} "
           f"{'steps':>10} {'saved':>8} {'cached':>7} {'chunks':>8} "
           f"{'MiB':>8}")
    print(hdr)
    print("-" * len(hdr))

    def row(name, cls, c):
        print(f"{name:<20} {cls:<12} {c['requests']:>8} "
              f"{c['lane_s']:>10.3f} {c['steps']:>10} "
              f"{c.get('steps_saved', 0):>8} {c.get('cached', 0):>7} "
              f"{c['chunks']:>8} {c['bytes_written'] / 2**20:>8.2f}")

    for tenant, t in sorted(payload["tenants"].items()):
        for cls, c in sorted(t["classes"].items()):
            row(tenant, cls, c)
    print("-" * len(hdr))
    row("TOTAL", "", payload["totals"])
    return 0


def _band_ok(ratio: float, tolerance: float) -> bool:
    """Symmetric relative band: ratio within [1-t, 1/(1-t)]."""
    lo = 1.0 - tolerance
    return lo <= ratio <= 1.0 / lo


def cmd_perfcheck(args) -> int:
    """The performance regression gate (CI/tooling satellite, ISSUE 8).

    Three layers, strict to informational:
    1. re-validate every committed lab JSON's *internal* gates (the
       claims the artifacts were committed with must still hold as
       recorded — a hand-edited or stale artifact fails loudly);
    2. run a fresh ``benchmarks/prof_overhead_lab.py`` and require its
       gates to pass AND its throughput to land within ``--tolerance``
       of the committed baseline (the band absorbs box-to-box jitter;
       the gates do not);
    3. cross-check the lab's recorded online cost model against the
       static ``calibration_v5e.json`` fit — a hard gate only when the
       lab ran on the calibrated platform, informational elsewhere
       (a CPU lab vs a TPU calibration is a sanity ratio, not a fail).
    """
    import json as _json
    import os
    import re as _re
    import subprocess
    import tempfile

    repo = Path(__file__).resolve().parent.parent
    bdir = repo / "benchmarks"
    baseline_path = (Path(args.baseline) if args.baseline
                     else bdir / "prof_overhead_lab.json")
    results: list[tuple[bool, str]] = []

    def check(ok: bool, name: str, detail: str) -> None:
        results.append((ok, f"{name}: {detail}"))

    if not baseline_path.exists():
        print(f"error: baseline {baseline_path} not found (run "
              f"benchmarks/prof_overhead_lab.py first, or pass "
              f"--baseline)", file=sys.stderr)
        return 2
    base = _json.loads(baseline_path.read_text())
    check(base.get("on_within_2pct_of_off") is True,
          "baseline overhead gate",
          f"observatory-on within 2% of off "
          f"(recorded {100 * base.get('on_overhead_frac', 0):+.2f}%)")
    check(bool(base.get("bit_identical_depth0"))
          and bool(base.get("bit_identical_depth2")),
          "baseline bit-identity",
          "npz outputs identical with observatory on vs off at depths "
          "0 and 2")
    check(base.get("usage_reconciles") is True, "baseline usage ledger",
          "ledger totals == sum of per-record usage stamps")

    # committed sibling labs: their internal gates, as recorded
    for fname, gates in (
            ("serve_lab.json",
             (("bit_identical_sample", lambda v: v is True),
              ("one_compile_per_bucket_lane_tier", lambda v: v is True),
              ("aggregate_speedup", lambda v: (v or 0) >= 3.0))),
            ("trace_overhead_lab.json",
             (("full_within_2pct_of_off", lambda v: v is True),
              ("trace_export_nonempty", lambda v: v is True))),
            ("serve_chaos_lab.json",
             (("bit_identical_healthy_sample", lambda v: v is True),
              ("healthy_within_10pct", lambda v: v is True),
              ("all_poisoned_quarantined", lambda v: v is True))),
            ("serve_frontend_lab.json",
             (("edf_vs_fifo_hit_rate_delta", lambda v: (v or -1) >= 0),)),
            ("serve_lane_kernel_lab.json",
             (("bit_identical", lambda v: v is True),
              ("solo_sample_identical", lambda v: v is True),
              ("zero_fallbacks", lambda v: v is True))),
            ("lane_kernel_compile_check.json",
             (("all_compile", lambda v: v is True),)),
            ("serve_mega_lab.json",
             (("mega_bit_identical", lambda v: v is True),
              ("zero_overflow_rejections", lambda v: v is True),
              ("packed_within_10pct", lambda v: v is True),
              ("packed_within_10pct_of_serve_lab", lambda v: v is True))),
            ("numerics_overhead_lab.json",
             (("on_within_2pct_of_off", lambda v: v is True),
              ("bit_identical_depth0", lambda v: v is True),
              ("bit_identical_depth2", lambda v: v is True),
              ("probe_verification_ok", lambda v: v is True))),
            ("serve_steady_lab.json",
             (("throughput_multiplier", lambda v: (v or 0) >= 1.5),
              ("steady_bit_identical", lambda v: v is True),
              ("colane_bit_identical", lambda v: v is True),
              ("zero_added_transfers", lambda v: v is True))),
            ("serve_resume_lab.json",
             (("resumed_bit_identical", lambda v: v is True),
              ("zero_resteps", lambda v: v is True),
              ("resumed_requests_recovered", lambda v: v is True))),
            ("serve_cache_lab.json",
             (("warm_speedup", lambda v: (v or 0) >= 5.0),
              ("full_hit_bit_identical", lambda v: v is True),
              ("prefix_delta_exact", lambda v: v is True),
              ("prefix_bit_identical", lambda v: v is True),
              ("cache_off_bit_identical", lambda v: v is True))),
            ("fleet_lab.json",
             (("speedup_2_backends", lambda v: (v or 0) >= 1.7),
              ("monotone_at_4", lambda v: v is True),
              ("fleet_bit_identical", lambda v: v is True),
              ("kill_zero_lost", lambda v: v is True),
              ("kill_zero_duplicates", lambda v: v is True),
              ("steal_recovered_requests", lambda v: (v or 0) >= 1),
              ("steal_recovery_s", lambda v: v is not None))),
            ("fleet_resilience_lab.json",
             (("flap_availability", lambda v: (v or 0) >= 0.99),
              ("flap_p99_ratio", lambda v: v is not None and v <= 1.5),
              ("flap_bit_identical", lambda v: v is True),
              ("cut_zero_lost", lambda v: v is True),
              ("cut_zero_duplicates", lambda v: v is True),
              ("hedges_won", lambda v: (v or 0) >= 1),
              ("hedge_bit_identical", lambda v: v is True),
              ("deadline_shed_exact", lambda v: v is True),
              ("breaker_steals_suppressed", lambda v: v is True)))):
        p = bdir / fname
        if not p.exists():
            check(False, fname, "committed artifact missing")
            continue
        d = _json.loads(p.read_text())
        for field, pred in gates:
            check(bool(pred(d.get(field))), f"{fname}",
                  f"{field}={d.get(field)}")

    fresh = None
    if args.fresh:
        out = Path(tempfile.mkdtemp(prefix="perfcheck_")) / "fresh.json"
        lab = bdir / "prof_overhead_lab.py"
        env = {**os.environ,
               "PYTHONPATH": str(repo) + os.pathsep
               + os.environ.get("PYTHONPATH", "")}
        rc = subprocess.call([sys.executable, str(lab), "--out", str(out)],
                             env=env, stdout=subprocess.DEVNULL)
        check(rc == 0 and out.exists(), "fresh lab run",
              f"prof_overhead_lab.py exited rc={rc}")
        if out.exists():
            fresh = _json.loads(out.read_text())
            check(fresh.get("on_within_2pct_of_off") is True,
                  "fresh overhead gate",
                  f"{100 * fresh.get('on_overhead_frac', 0):+.2f}% "
                  f"(gate <= +2%)")
            check(bool(fresh.get("bit_identical_depth0"))
                  and bool(fresh.get("bit_identical_depth2")),
                  "fresh bit-identity", "npz on-vs-off at depths 0 and 2")
            b_pts = (base.get("on") or {}).get("points_per_s") or 0
            f_pts = (fresh.get("on") or {}).get("points_per_s") or 0
            if b_pts and f_pts:
                ratio = f_pts / b_pts
                check(_band_ok(ratio, args.tolerance),
                      "fresh-vs-baseline band",
                      f"throughput ratio {ratio:.3f} (tolerance "
                      f"±{100 * args.tolerance:.0f}%)")
            else:
                check(False, "fresh-vs-baseline band",
                      "points_per_s missing from lab output")

    if args.fresh:
        # dynamic lockcheck overhead (ISSUE 11): the HEAT_TPU_LOCKCHECK=1
        # watchdog wraps every engine/observatory lock in per-acquire
        # bookkeeping — it must stay noise-level on a serve wave (it is
        # meant to ride the chaos suite and soak tests, not to be a mode
        # you budget for). Interleaved best-of-2 walls, in-process: the
        # env flag is read at lock CREATION, so each engine picks up its
        # own mode. Also a correctness gate: the armed waves must record
        # zero lock-order inversions.
        import time as _time

        from .config import HeatConfig
        from .runtime import debug as _debug
        from .serve import Engine, ServeConfig

        def _wave() -> float:
            eng = Engine(ServeConfig(lanes=4, chunk=8, buckets=(64,),
                                     emit_records=False))
            for i in range(12):
                eng.submit(HeatConfig(n=48, ntime=96, dtype="float32",
                                      ic="hat", bc="edges"))
            t0 = _time.perf_counter()
            eng.run()
            return _time.perf_counter() - t0

        _debug.reset_lock_order_stats()
        walls = {"off": [], "on": []}
        prev = os.environ.pop("HEAT_TPU_LOCKCHECK", None)
        try:
            for mode in ("off", "on", "off", "on"):
                if mode == "on":
                    os.environ["HEAT_TPU_LOCKCHECK"] = "1"
                else:
                    os.environ.pop("HEAT_TPU_LOCKCHECK", None)
                walls[mode].append(_wave())
        finally:
            if prev is None:
                os.environ.pop("HEAT_TPU_LOCKCHECK", None)
            else:
                os.environ["HEAT_TPU_LOCKCHECK"] = prev
        ratio = min(walls["on"]) / min(walls["off"])
        check(_band_ok(ratio, max(args.tolerance, 0.5)),
              "lockcheck overhead",
              f"serve wave with the lock-order watchdog armed runs at "
              f"{ratio:.3f}x the unarmed wall (noise-level band)")
        stats = _debug.lock_order_stats()
        check(not stats["violations"], "lockcheck inversions",
              f"zero lock-order inversions under the armed waves "
              f"(saw {len(stats['violations'])}; edges observed: "
              f"{len(stats['edges'])})")

        # dynamic racecheck overhead (ISSUE 14): same shape as the
        # lockcheck gate — HEAT_TPU_RACECHECK=1 swaps the thread-shared
        # objects onto instrumented classes whose __getattribute__/
        # __setattr__ maintain Eraser candidate locksets, and that must
        # stay affordable on a serve wave (it rides the chaos suite,
        # not production). Correctness gate too: the armed waves must
        # surface zero race findings.
        _debug.reset_race_stats()
        walls = {"off": [], "on": []}
        prev = os.environ.pop("HEAT_TPU_RACECHECK", None)
        try:
            for mode in ("off", "on", "off", "on"):
                if mode == "on":
                    # "record" arms the same instrumentation as "1" but
                    # logs findings instead of raising, so a regression
                    # fails the gate below rather than crashing the wave
                    os.environ["HEAT_TPU_RACECHECK"] = "record"
                else:
                    os.environ.pop("HEAT_TPU_RACECHECK", None)
                walls[mode].append(_wave())
        finally:
            if prev is None:
                os.environ.pop("HEAT_TPU_RACECHECK", None)
            else:
                os.environ["HEAT_TPU_RACECHECK"] = prev
        ratio = min(walls["on"]) / min(walls["off"])
        check(_band_ok(ratio, max(args.tolerance, 0.5)),
              "racecheck overhead",
              f"serve wave with the race sanitizer armed runs at "
              f"{ratio:.3f}x the unarmed wall (noise-level band)")
        rstats = _debug.race_stats()
        check(not rstats["findings"], "racecheck findings",
              f"zero race findings under the armed waves "
              f"(saw {len(rstats['findings'])}; objects instrumented: "
              f"{rstats['instrumented']})")
        _debug.reset_race_stats()

    # lane-kernel cost rows (ISSUE 9): the committed kernel A/B must be
    # internally consistent — the cost model's kernel-keyed rows imply
    # the same pallas/xla cost ratio the measured drain walls show, and
    # on a TPU artifact the Pallas lane program must have won outright
    lane_path = bdir / "serve_lane_kernel_lab.json"
    if lane_path.exists():
        lane = _json.loads(lane_path.read_text())

        def _agg_s_per_lane_step(side: dict):
            # work-weighted mean over the side's kernel-keyed cost rows
            wall = steps = 0.0
            for e in side.get("cost_model") or []:
                m = e.get("mean_s_per_lane_step")
                if m and e.get("wall_s"):
                    wall += e["wall_s"]
                    steps += e["wall_s"] / m
            return wall / steps if steps else None

        want = {"pallas": "pallas", "xla": "xla"}
        keyed_ok = all(
            {e.get("kernel") for e in
             (lane.get(side) or {}).get("cost_model") or []} <= {kern}
            for side, kern in want.items())
        check(keyed_ok, "lane-kernel cost rows",
              "each A/B side's cost-model rows carry its own kernel key")
        agg_p = _agg_s_per_lane_step(lane.get("pallas") or {})
        agg_x = _agg_s_per_lane_step(lane.get("xla") or {})
        wall_p = ((lane.get("pallas") or {}).get("wall_s", 0)
                  - (lane.get("pallas") or {}).get("compile_s", 0))
        wall_x = ((lane.get("xla") or {}).get("wall_s", 0)
                  - (lane.get("xla") or {}).get("compile_s", 0))
        if agg_p and agg_x and wall_p > 0 and wall_x > 0:
            # sanity band, same spirit as the calibration cross-check's
            # 0.25-4x: the kernel-keyed cost rows and the compile-
            # excluded drain walls measure the same A/B through
            # different lenses (chunk service vs end-to-end with host
            # bookkeeping) — they may disagree by a dilution factor,
            # but an order-of-magnitude split means one of them lies
            ratio = (agg_p / agg_x) / (wall_p / wall_x)
            check(0.25 <= ratio <= 4.0,
                  "lane-kernel cost band",
                  f"cost-model pallas/xla ratio vs compile-excluded "
                  f"wall ratio within 4x (consistency {ratio:.3f})")
        else:
            check(False, "lane-kernel cost band",
                  "cost-model rows or walls missing from the artifact")
        if str(lane.get("platform")) == "tpu":
            check(lane.get("pallas_beats_xla") is True,
                  "lane-kernel TPU gate",
                  f"pallas_vs_xla={lane.get('pallas_vs_xla')} (must beat "
                  f"the XLA lane program per chip on TPU)")
        else:
            check(True, "lane-kernel perf (informational, platform="
                  f"{lane.get('platform')})",
                  f"pallas_vs_xla={lane.get('pallas_vs_xla')}, "
                  f"pallas_vs_solo={lane.get('pallas_vs_solo')}")

    # cost model vs the static calibration fit
    cal_path = bdir / "calibration_v5e.json"
    cm = (fresh or base).get("cost_model") or []
    if cal_path.exists() and cm:
        cal = _json.loads(cal_path.read_text())
        cal_pts = (cal.get("sweep_2d") or {}).get("points_per_s")
        on_tpu = str((fresh or base).get("platform", "")) == "tpu"
        for e in cm:
            m = _re.match(r"(\d)d/n(\d+)/", e["bucket"])
            per = e.get("ewma_s_per_lane_step")
            if not m or not per or not cal_pts:
                continue
            ndim, side = int(m.group(1)), int(m.group(2))
            implied = side**ndim / per
            ratio = implied / cal_pts
            line = (f"bucket {e['bucket']}: cost model implies "
                    f"{implied:.3e} pts/s = {100 * ratio:.2f}% of the "
                    f"calibrated v5e stencil rate")
            if on_tpu:
                # live model within 4x of the one-off fit: lanes pay
                # masking/vmap overhead vs the solo Pallas kernel, but an
                # order-of-magnitude gap means one of the two is wrong
                check(0.25 <= ratio <= 4.0, "calibration cross-check",
                      line)
            else:
                check(True, "calibration cross-check (informational, "
                      f"platform={(fresh or base).get('platform')})", line)

    # cost model vs the program auditor's static roofline prior (ISSUE
    # 13): the audit registry carries a bytes/bandwidth floor per lane
    # bucket computed from the jaxpr-level traffic model — no
    # measurement at all — so learned-vs-static agreement within an
    # order of magnitude catches a units bug in EITHER model
    if cm:
        from .runtime.prof import static_prior_s_per_lane_step
        on_tpu = str((fresh or base).get("platform", "")) == "tpu"
        for e in cm:
            per = e.get("ewma_s_per_lane_step")
            prior = static_prior_s_per_lane_step(
                e.get("bucket", ""), e.get("kernel", "xla"))
            if not per or not prior:
                continue
            ratio = per / prior
            line = (f"bucket {e['bucket']}: learned "
                    f"{per:.3e}s/lane-step = {ratio:.2f}x the static "
                    f"roofline prior {prior:.3e}s")
            if on_tpu:
                # the prior is a bandwidth floor for the chip the model
                # was calibrated against, so 0.1-10x is generous — only
                # a units/exponent bug escapes it
                check(0.1 <= ratio <= 10.0, "static-prior band", line)
            else:
                check(True, "static-prior band (informational, "
                      f"platform={(fresh or base).get('platform')})",
                      line)

    failed = [line for ok, line in results if not ok]
    for ok, line in results:
        print(("OK   " if ok else "FAIL ") + line)
    print(f"perfcheck: {'OK' if not failed else 'FAILED'} — "
          f"{len(results) - len(failed)}/{len(results)} checks passed")
    return 0 if not failed else 1


def cmd_check(args) -> int:
    """The invariant guard (ISSUE 11): run the AST-based checker suite
    over the package source. Exit codes: 0 clean, 1 violations, 2 usage
    error — batch drivers and ``make check`` key off them."""
    import json as _json

    from .analysis import RULE_DOCS, RULE_FAMILIES, run_checks

    if args.list_rules:
        for rid in sorted(RULE_FAMILIES):
            print(f"{rid:<22} {RULE_DOCS[rid]}")
        return 0
    root = Path(args.root) if args.root else Path(__file__).resolve().parent
    if not root.is_dir():
        print(f"error: {root} is not a directory", file=sys.stderr)
        return 2
    if args.dead_code:
        from .analysis.deadcode import dead_code_report
        rows = dead_code_report(root)
        if args.json:
            print(_json.dumps({"dead_code": rows}, sort_keys=True))
            return 0
        for row in rows:
            print(f"{row['path']}:{row['line']}: {row['qualname']} — "
                  "public function unreachable from any entry point")
        print(f"heat-tpu check --dead-code: {len(rows)} candidate(s) "
              "(informational — the reachability closure is "
              "conservative, so these really are unreferenced)")
        return 0
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    try:
        violations, stats = run_checks(root, rules=rules,
                                       update_schemas=args.update_schemas,
                                       strict_allows=args.strict_allows)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(_json.dumps({"stats": stats,
                           "violations": [dataclasses.asdict(v)
                                          for v in violations]},
                          sort_keys=True))
        return 0 if not violations else 1
    if not args.strict_allows:
        for s in stats.get("stale_allows", ()):
            print(f"warning: {s['path']}:{s['line']}: stale "
                  f"allow[{s['rule']}] marker — {s['why']} "
                  "(--strict-allows makes this fail)")
    for v in violations:
        print(v.format())
    per = ", ".join(f"{r}={n}" for r, n in sorted(stats["per_rule"].items())
                    if n) or "none"
    verdict = "OK" if not violations else "FAILED"
    print(f"heat-tpu check: {verdict} — {stats['files']} file(s), "
          f"{len(stats['rules'])} rule famil"
          f"{'y' if len(stats['rules']) == 1 else 'ies'}, "
          f"{stats['allow_markers']} allow marker(s), "
          f"{stats['violations']} violation(s)"
          + (f" ({per})" if violations else "")
          + ("; schema registry rewritten — review & commit the diff"
             if args.update_schemas else ""))
    if violations:
        print("each line is path:line: [rule] message; sanctioned "
              "exceptions take a `# heat-tpu: allow[rule] reason` marker "
              "— see TROUBLESHOOTING.md 'Static analysis'")
    return 0 if not violations else 1


def cmd_audit(args) -> int:
    """The program auditor (ISSUE 13): trace every registered program
    family to jaxprs/StableHLO on abstract inputs — no execution — and
    machine-check the contracts the AST tier cannot see (donation,
    traced purity, dtype discipline, compile budget, digest drift).
    Exit codes mirror ``check``: 0 clean, 1 violations, 2 usage error."""
    import json as _json

    from .analysis.programs import CONTRACTS, FAST_CONTRACTS, audit

    if args.list_contracts:
        for cid, doc in sorted(CONTRACTS.items()):
            print(f"{cid:<18} {doc}")
        return 0
    contracts = ([c.strip() for c in args.contracts.split(",") if c.strip()]
                 if args.contracts else None)
    if args.fast:
        if contracts:
            print("error: --fast and --contracts are mutually exclusive",
                  file=sys.stderr)
            return 2
        contracts = list(FAST_CONTRACTS)
    try:
        violations, report = audit(registry_path=args.registry,
                                   update_digests=args.update_digests,
                                   contracts=contracts)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        report["violation_list"] = [dataclasses.asdict(v)
                                    for v in violations]
        print(_json.dumps(report, sort_keys=True))
        return 0 if not violations else 1
    for v in violations:
        print(v.format())
    enum = report["budget"]["enumerated"]
    verdict = "OK" if not violations else "FAILED"
    print(f"heat-tpu audit: {verdict} — "
          f"{report['traced']}/{report['families']} families traced, "
          f"{len(report['contracts'])} contract"
          f"{'' if len(report['contracts']) == 1 else 's'}, "
          f"digest gate {report['digest_gate']}, budget "
          f"declared={report['budget']['declared']} "
          f"enumerated={enum['total'] if enum else 'n/a'}, "
          f"{report['violations']} violation(s)"
          + ("; digest registry rewritten — review & commit the diff"
             if args.update_digests else ""))
    if violations:
        print("see TROUBLESHOOTING.md 'Program audit' — intentional "
              "program changes go through `heat-tpu audit "
              "--update-digests` so the jaxpr diff is reviewed")
    return 0 if not violations else 1


def cmd_trace(args) -> int:
    """Text timeline summary of any trace file this framework writes
    (--trace exports, flight-recorder dumps, /tracez responses) — the
    no-browser half of the observability story: per-lane utilization,
    top queue-wait requests, boundary-fetch/device-idle wall, notable
    fault instants."""
    path = Path(args.tracefile)
    if not path.exists():
        print(f"error: {path} not found", file=sys.stderr)
        return 2
    try:
        lines = trace_mod.summarize_file(path, top=args.top)
    except (ValueError, KeyError, TypeError, AttributeError) as e:
        print(f"error: {path} is not a Chrome trace-event JSON file "
              f"({type(e).__name__}: {e})", file=sys.stderr)
        return 2
    for line in lines:
        print(line)
    if "flightrec" in path.name:
        # flight dumps exist because something fired: name the likely
        # trigger from the notable instants so triage starts with a
        # cause, not a timeline scroll (priority: a numerics violation
        # explains any quarantine that followed it)
        ev_line = next((ln for ln in lines if ln.startswith("events: ")),
                       "")
        for marker, label in (
                ("numerics-violation", "numerics violation — the field "
                 "is finite but un-physical (numerics_violation records "
                 "carry the witnesses; TROUBLESHOOTING.md)"),
                ("watchdog-fired", "boundary-fetch watchdog timeout"),
                ("quarantine", "lane quarantine (nonfinite / rollback "
                 "budget exhausted)"),
                ("rollback", "NaN rollback")):
            if marker in ev_line:
                print(f"flight-dump triage: {marker} instant(s) present "
                      f"— likely trigger: {label}")
                break
    return 0


def cmd_plan(args) -> int:
    """Dry explanation of the execution plan — no device is touched.

    The observability counterpart of the reference's decomposition
    announcements (mpi+cuda/heat.F90:90,239-240), extended to the kernel
    planner: which stencil kernel the pallas dispatch would pick and its
    tile/halo geometry, or the sharded backend's mesh/halo economics.
    """
    import numpy as np

    path = Path(args.input)
    if not path.exists():
        print(f"error: {path} not found", file=sys.stderr)
        return 2
    cfg = parse_input(path)
    if args.variant:
        cfg = variant_config(args.variant, cfg)
    cfg = _apply_overrides(cfg, args)

    print(f"config: n={cfg.n}^{cfg.ndim} dtype={cfg.dtype} "
          f"ntime={cfg.ntime} backend={cfg.backend}")
    _warn_if_unstable(cfg)
    if cfg.bc == "periodic":
        # the pbc=.true. topology (mpi_cart_create periods,
        # mpi+cuda/heat.F90:76,97): closed ppermute ring, nothing pinned
        print("topology: periodic (torus) — bc_value unused, "
              "total heat conserved exactly")
    item = {"float64": 8, "float32": 4, "bfloat16": 2}[cfg.dtype]

    # one mesh/fuse-width derivation, validated like the run path would
    mesh_shape = w = None
    if cfg.backend == "sharded":
        from .backends.sharded import fuse_depth_sharded
        from .parallel.mesh import auto_mesh_shape

        mesh_shape = cfg.mesh_shape
        assumed = ""
        if mesh_shape is None:
            mesh_shape = auto_mesh_shape(8, cfg.ndim)
            assumed = " (auto; assuming 8 devices)"
        if len(mesh_shape) != cfg.ndim:
            print(f"error: mesh {mesh_shape} must have {cfg.ndim} dims",
                  file=sys.stderr)
            return 2
        for s in mesh_shape:
            if cfg.n % s != 0:
                print(f"error: grid {cfg.n} does not divide evenly over "
                      f"mesh axis of size {s} (run would reject this too)",
                      file=sys.stderr)
                return 2
        w = fuse_depth_sharded(cfg, mesh_shape)
        local = tuple(cfg.n // s for s in mesh_shape)
        print(f"mesh: {mesh_shape}{assumed}, "
              f"local block {'x'.join(map(str, local))}")

    if cfg.backend in ("pallas", "sharded"):
        from .ops.pallas_stencil import pallas_available, plan_summary
        from .utils import jnp_dtype

        # mirror the run path's kernel gate exactly: the sharded backend
        # gates on the GLOBAL shape + local_kernel (sharded.py
        # make_local_multistep); geometry then describes the shape the
        # kernel actually sees (the halo-padded local block; ghost BC on
        # the pallas backend pads the global field by one)
        gate_ok = pallas_available(cfg.shape, jnp_dtype(cfg.dtype))
        if cfg.backend == "sharded":
            if cfg.local_kernel == "pallas" and not gate_ok:
                # the run path rejects this outright (make_local_multistep)
                print(f"error: local_kernel='pallas' does not support "
                      f"dtype={cfg.dtype!r} (run would reject this too)",
                      file=sys.stderr)
                return 2
            if cfg.local_kernel == "xla" or not gate_ok:
                print("kernel: XLA mini-step path (local_kernel="
                      f"{cfg.local_kernel}, pallas gate "
                      f"{'ok' if gate_ok else 'unavailable'})")
            else:
                shape = tuple(l + 2 * w for l in local)
                print("kernel (on TPU; auto falls back to XLA elsewhere): "
                      + plan_summary(shape, cfg.dtype, w))
        else:
            from .backends.pallas import fuse_depth

            shape = cfg.shape
            if cfg.bc == "ghost" and gate_ok:
                shape = tuple(s + 2 for s in shape)  # frozen ghost ring
            elif cfg.bc == "periodic" and gate_ok:
                from .ops.pallas_stencil import periodic_pad_width

                # wrap-ghost ring of the chunked fuse width — the kernel's
                # own derivation (ftcs_multistep_periodic_pallas)
                w_ring = periodic_pad_width(shape, fuse_depth(cfg))
                shape = tuple(s + 2 * w_ring for s in shape)
            # plan_summary reports the XLA fallback itself when no kernel
            # plan exists for the shape/dtype
            print("kernel: " + plan_summary(shape, cfg.dtype,
                                            fuse_depth(cfg)))

    if cfg.backend == "sharded":
        slab_cells = 2 * w * sum(
            int(np.prod(local)) // l for l in local)
        print(f"halo: width {w} every {w} steps -> "
              f"{slab_cells * item / 2**10:.1f} KiB sent/shard/exchange "
              f"({slab_cells * item / w / 2**10:.2f} KiB/step amortized)")
    return 0


def cmd_launch(args) -> int:
    """Spawn N local worker processes joined into one jax.distributed world,
    under a self-healing supervisor.

    World plumbing == the reference's mpirun contract: every worker runs the
    same program (SPMD), rank from JAX_PROCESS_ID, world size from
    JAX_NUM_PROCESSES, rendezvous at the coordinator (≙ MPI_Init,
    fortran/mpi+cuda/heat.F90:60-62). Worker 0's output streams through
    (master-gated prints, like the reference's masterproc writes); all
    workers' files land in the current directory (per-shard soln dumps).

    Supervision (the part the reference's ignored MPI error codes never
    had): a mid-run worker death stops the surviving world (a dead peer
    leaves survivors blocked in collective rendezvous — they cannot make
    progress and must be killed, reaped, and restarted), validates and
    quarantines the checkpoint directory (``checkpoint.scan_resume_step``),
    and relaunches with resume under exponential backoff, up to
    ``--max-restarts`` times, emitting a structured JSON restart record per
    attempt. A deadline exit (rc=124) is never restarted. Relaunched
    workers get ``HEAT_TPU_RESTART=<attempt>`` so restart-gated injected
    faults (runtime/faults.py) don't re-fire in the healed world.
    """
    import json as _json
    import os
    import socket
    import subprocess
    import sys as _sys
    import time as _time

    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("launch: missing worker arguments (e.g. "
              "`heat-tpu launch -n 2 run --backend sharded`)",
              file=sys.stderr)
        return 2
    if cmd[0] == "run":
        # force the CPU platform in-process (a JAX_PLATFORMS env var is
        # overridden where a site hook pins a TPU plugin) and size each
        # worker's device contribution
        cmd = cmd + ["--virtual-devices", str(args.devices_per_process)]

    # --deadline wins over the env knob (documented in TROUBLESHOOTING.md);
    # it bounds each ATTEMPT, not the supervisor's whole lifetime
    deadline_s = (args.deadline if args.deadline is not None
                  else int(os.environ.get("HEAT_TPU_LAUNCH_TIMEOUT_S", "3600")))

    # supervisor-side view of the workers' checkpoint setup, for restart
    # records and pre-relaunch validation/quarantine (workers re-validate
    # with the full config fingerprint on their own resume path)
    ckpt_dir = None
    if "--checkpoint-every" in cmd or "--checkpoint-dir" in cmd:
        ckpt_dir = "checkpoints"
        if "--checkpoint-dir" in cmd:
            try:
                ckpt_dir = cmd[cmd.index("--checkpoint-dir") + 1]
            except IndexError:
                pass

    def spawn_world(restart: int):
        # probe-then-release port allocation is racy (another process can
        # grab it before the coordinator binds); the quick-failure retry
        # below absorbs exactly that class of loss
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        env = {
            **os.environ,
            # workers must import the same heat_tpu the launcher runs, even
            # when it is only on the launcher's sys.path (not installed)
            "PYTHONPATH": str(Path(__file__).resolve().parent.parent)
            + os.pathsep + os.environ.get("PYTHONPATH", ""),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices_per_process}",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": str(args.processes),
            # incarnation counter: restart-gated injected faults key off it
            "HEAT_TPU_RESTART": str(restart),
        }
        # worker 0's stdout streams (master-gated prints); every worker's
        # stderr interleaves, like mpirun, so rank>0 failures keep their
        # tracebacks
        return [
            subprocess.Popen(
                [_sys.executable, "-m", "heat_tpu", *cmd],
                env={**env, "JAX_PROCESS_ID": str(i)},
                stdout=None if i == 0 else subprocess.DEVNULL,
            )
            for i in range(args.processes)
        ]

    def run_world(procs):
        """Wait all workers; on first failure or deadline, stop the rest
        (a dead peer leaves survivors blocked in collective rendezvous).
        Returns (rc, elapsed_s, reason) — reason is "deadline" for the
        rc=124 budget exit, else the first dead worker's identity."""
        t0 = _time.monotonic()
        live = dict(enumerate(procs))
        rc = 0
        reason = None
        while live:
            for i, p in sorted(live.items()):
                if p.poll() is not None:
                    del live[i]
                    if p.returncode != 0 and rc == 0:
                        print(f"launch: worker {i} exited "
                              f"rc={p.returncode}", file=sys.stderr)
                        rc = p.returncode
                        reason = f"worker {i} exited rc={p.returncode}"
            if rc or _time.monotonic() - t0 > deadline_s:
                if not rc:
                    rc = 124
                    reason = "deadline"
                    print(f"launch: deadline {deadline_s}s exceeded — "
                          f"stopping {len(live)} live worker(s) (rc=124: "
                          f"budget exit, not a crash)", file=sys.stderr)
                for p in live.values():
                    p.terminate()
                for p in live.values():
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.wait()  # reap: a SIGKILLed worker must not
                        # linger as a zombie for the supervisor's lifetime
                break
            _time.sleep(0.05)
        return rc, _time.monotonic() - t0, reason

    from .runtime import checkpoint

    backoff_base = float(os.environ.get("HEAT_TPU_RESTART_BACKOFF_S", "0.5"))
    restarts = 0
    startup_retry_used = False
    while True:
        rc, elapsed, reason = run_world(spawn_world(restarts))
        if rc == 0:
            return 0
        if reason == "deadline":
            return rc  # a budget, not a fault: restarting cannot help
        # newest world-complete, loadable, finite checkpoint step (corrupt
        # candidates are quarantined to *.corrupt right here, so the
        # relaunch falls back to the next-older step instead of tripping)
        resume_step = (checkpoint.scan_resume_step(
            ckpt_dir, nprocs=args.processes) if ckpt_dir else None)
        if resume_step is None and elapsed < 30 and not startup_retry_used:
            # startup-class failure (port race, env): one clean retry on a
            # fresh port, outside the restart budget
            startup_retry_used = True
            print("launch: startup failure, retrying once on a fresh port",
                  file=sys.stderr)
            continue
        if restarts >= args.max_restarts:
            if args.max_restarts > 0:
                print(f"launch: giving up after {restarts} restart(s) "
                      f"(--max-restarts {args.max_restarts})",
                      file=sys.stderr)
            return rc
        restarts += 1
        backoff = min(backoff_base * 2 ** (restarts - 1), 30.0)
        rec = {"event": "launch_restart", "attempt": restarts,
               "max_restarts": args.max_restarts, "reason": reason,
               "rc": rc, "elapsed_s": round(elapsed, 3),
               "resume_step": resume_step, "backoff_s": backoff}
        print("launch: restart " + _json.dumps(rec), file=sys.stderr,
              flush=True)
        _time.sleep(backoff)


def cmd_viz(args) -> int:
    from .viz import render_dat

    out = render_dat(args.datfile, args.save, ndim=args.ndim)
    print(f"wrote {out}")
    return 0


def cmd_bench(args) -> int:
    """Inline headline benchmark (shared core with the repo-root bench.py,
    heat_tpu/benchmark.py). Defaults shrink off-TPU so the command stays
    interactive on a laptop/CI host."""
    import json as _json

    import jax

    from .benchmark import N, STEPS, headline_measure

    if args.repeats < 1:
        print("bench: --repeats must be >= 1", file=sys.stderr)
        return 2
    on_tpu = jax.default_backend() == "tpu"
    n = args.n or (N if on_tpu else 512)
    steps = args.steps or (STEPS if on_tpu else 256)
    rec = headline_measure(n=n, steps=steps, repeats=args.repeats)
    if rec["platform"] == "tpu":
        print(f"{rec['value']:.4g} points/s "
              f"({100 * rec['vs_baseline']:.0f}% of the "
              f"one-pass v5e HBM roofline; raw single-call "
              f"{rec['raw_single_call']:.4g}) on {rec['platform']}")
    else:
        # the 819 GB/s roofline constant is meaningless off-TPU (and the
        # shrunken default sizes make the percentage nonsense) — report the
        # raw rate only; the JSON record keeps every field for tooling
        print(f"{rec['value']:.4g} points/s on {rec['platform']} "
              f"(raw single-call {rec['raw_single_call']:.4g}; roofline % "
              f"only meaningful on TPU)")
    print(_json.dumps(rec))
    return 0


def cmd_info(_args) -> int:
    import jax

    from . import machine
    from .io.native import native_available

    print(f"jax {jax.__version__}, backend={jax.default_backend()}")
    print(f"devices: {jax.devices()}")
    chip = machine.current()
    print(f"machine model: {chip.label} — HBM "
          f"{chip.hbm_bytes_per_s / 1e9:.0f} GB/s, one-pass roofline "
          f"{chip.roofline_points_per_s('float32'):.3e} f32 pts/s"
          + ("" if chip.calibrated else " — spec-derived table"))
    print(f"process {jax.process_index()}/{jax.process_count()}")
    print(f"native fastio: {'available' if native_available() else 'unavailable (numpy fallback)'}")

    # gloo CPU collectives: the multi-process-CPU prerequisite the launch
    # path selects automatically — surfaced here so its absence is visible
    # BEFORE a `heat-tpu launch -n 2` dies at its first cross-process jit
    from .parallel.dist import cpu_collectives_info

    cc = cpu_collectives_info()
    if cc["available"]:
        detail = f"selected={cc['value'] or 'none'}"
        if cc["env_override"]:
            detail += " (pinned via JAX_CPU_COLLECTIVES_IMPLEMENTATION)"
        elif (cc["value"] or "none") == "none":
            detail += " (heat-tpu launch selects gloo automatically)"
        print(f"gloo CPU collectives: available — {detail}")
    else:
        print("gloo CPU collectives: UNAVAILABLE (pre-gloo jaxlib) — "
              "multi-process CPU worlds cannot compile cross-process "
              "programs; `heat-tpu launch` sharded runs will fail")

    # serve execution defaults: what a `heat-tpu serve` run will do before
    # any knob is passed (the per-run counters — chunks dispatched,
    # boundary waits, tail chunks — print on every serve invocation and in
    # Engine.summary(); this line is the static half of that story)
    from .serve import ServeConfig
    from .serve.engine import tail_size

    _sd = ServeConfig()
    print(f"serve defaults: dispatch depth 2 (pipelined; --dispatch-depth "
          f"off = sync fallback), {_sd.lanes} lanes (power-of-two tiers), "
          f"chunk {_sd.chunk} (+{tail_size(_sd.chunk)}-step tail program, "
          f"compiled on first use), buckets {','.join(map(str, _sd.buckets))}")
    # two-tier placement (ISSUE 10): where a bucket-overflow request goes
    # on THIS host — the mesh a mega-lane would span, the auto default,
    # and the packed ceiling it takes over from
    from .parallel.mesh import auto_mesh_shape

    _ndev = len(jax.devices())
    _mshape = "x".join(map(str, auto_mesh_shape(_ndev, 2)))
    _mega_default = 1 if _ndev > 1 else 0
    print(f"serve placement: two-tier — packed vmapped lanes up to bucket "
          f"{max(_sd.buckets)}, then sharded mega-lanes spanning the "
          f"{_ndev}-device mesh ({_mshape} for 2D); mega-lanes default "
          f"{_mega_default} on this host (--mega-lanes auto|N; 0 = "
          f"overflow stays a rejection"
          + (", the single-device behavior); "
             if _ndev <= 1 else "); ")
          + "mega side must divide the mesh axes")
    # serve lane-kernel defaults/availability: which chunk-program body
    # each default bucket would get under --serve-lane-kernel auto on
    # THIS host (the static half; per-run fallbacks print per serve)
    from .ops.pallas_stencil import lane_kernel_available

    _on_tpu = jax.default_backend() == "tpu"
    _plans = ", ".join(
        f"{b}:{'ok' if lane_kernel_available(2, b, 'float32') else 'none'}"
        for b in _sd.buckets)
    print(f"serve lane-kernel: {_sd.lane_kernel} (--serve-lane-kernel "
          f"auto|pallas|xla; auto = Pallas on TPU where the bucket has a "
          f"kernel plan, XLA elsewhere) — this host: "
          f"{'TPU, auto resolves Pallas per plan' if _on_tpu else 'no TPU, auto resolves XLA'}; "
          f"2D f32 lane plans {_plans}; f64 always XLA (no VPU f64); "
          f"unavailable buckets degrade loudly (lane_kernel_fallback)")
    print(f"serve fault domains: on-nan={_sd.on_nan} (--serve-on-nan "
          f"rollback = per-lane restore-and-re-step, 2 retries), "
          f"deadline={'none' if _sd.deadline_ms is None else _sd.deadline_ms} "
          f"(--serve-deadline MS / per-request deadline_ms), "
          f"max-queue={'unbounded' if not _sd.max_queue else _sd.max_queue}, "
          f"fetch watchdog {_sd.fetch_timeout_s:g}s (per-lane isfinite "
          f"bits ride every boundary fetch — no extra D2H)")

    # tracing defaults: the always-on flight recorder and the opt-in
    # Perfetto export (the dynamic half — dumps actually written, /tracez
    # hits — shows up in serve output and the gateway log)
    print(f"trace defaults: flight recorder on (ring of "
          f"{trace_mod.DEFAULT_BUFFER} events; dumps flightrec-*.trace.json "
          f"on watchdog/quarantine-after-rollbacks/numerics-violation/"
          f"scheduler-crash), "
          f"--trace FILE / HEAT_TPU_TRACE=FILE exports Chrome trace JSON "
          f"(Perfetto), GET /tracez on the gateway, `heat-tpu trace FILE` "
          f"for a text summary; HEAT_TPU_TRACE=off / --trace-buffer 0 "
          f"disables")

    # performance & cost observatory (runtime/prof.py): the metering
    # defaults plus this process's compile-observatory state (mostly
    # cold at info time — the line says where the warm numbers surface)
    from .config import SLO_CLASSES as _slo_classes
    from .config import SLO_TARGETS
    from .runtime import prof as _prof

    _comp = _prof.compile_log().summary()
    _targets = ",".join(f"{c}={t:g}" for c, t in sorted(
        SLO_TARGETS.items(), key=lambda kv: _slo_classes.get(kv[0], 99)))
    print(f"perf observatory: on by default (--prof off = A/B baseline) "
          f"— online chunk-cost model per (bucket, lane-tier, depth), "
          f"per-tenant usage ledger (GET /v1/usage, heat-tpu usage), "
          f"memory watermarks every {_sd.mem_poll_every} boundaries "
          f"(--mem-poll), SLO burn monitor (targets {_targets}, "
          f"--slo-targets); surfaces: /metrics, GET /statusz, "
          f"Engine.summary(), heat-tpu perfcheck")
    print(f"compile observatory: {_comp['programs']} program(s) compiled "
          f"by this process ({_comp['total_s']:.2f}s; "
          f"{_comp['first_s']:.2f}s first-time, {_comp['warm_s']:.2f}s "
          f"warm) — structured per-compile events ride trace spans and "
          f"/metrics; per-program keys in GET /statusz")

    # numerics observatory + canary prober (ISSUE 15): the solution-
    # quality defaults — the dynamic half (steady/violation records,
    # probe verdicts) prints per serve run and on /metrics, /statusz
    from .runtime.numerics import ENVELOPE_TOL as _env_tol

    print(f"numerics observatory: on by default (--numerics off = A/B "
          f"baseline) — per-lane residual/min/max/heat stats ride the "
          f"boundary vector (no extra device passes or transfers), "
          f"steady-tol {_sd.steady_tol:g} (--steady-tol), guard "
          f"{_sd.numerics_guard} (--numerics-guard warn|quarantine), "
          f"max-principle tol f32 {_env_tol['float32']:g} / bf16 "
          f"{_env_tol['bfloat16']:g} of envelope scale; overhead gate "
          f"benchmarks/numerics_overhead_lab.json")
    print(f"semantic scheduling: until=steady requests (request 'until'/"
          f"'tol' fields) retire at the first chunk boundary whose "
          f"residual EWMA passes tolerance (exit=steady, steps_done < "
          f"requested, bit-identical to the truncated fixed-step run); "
          f"eigenmode ETA predictor (runtime/convergence.py) feeds EDF "
          f"ordering, wall forecasts and dispatch sizing; savings on "
          f"/metrics heat_tpu_serve_steps_saved_total and the usage "
          f"ledger; gate benchmarks/serve_steady_lab.json")
    print(f"prober: off by default (--probe-interval S, needs --listen) "
          f"— sine-eigenmode known-answer canary through the real "
          f"gateway under tenant '_probe', verified against the closed-"
          f"form lambda**s decay (grid.sine_decay_factor); "
          f"probe_result/probe_failed records, /metrics heat_tpu_probe_*")

    # online gateway defaults (`heat-tpu serve --listen HOST:PORT`): the
    # admission policy and SLO-class table requests are validated against
    from .config import SLO_CLASSES

    _classes = ">".join(sorted(SLO_CLASSES, key=SLO_CLASSES.get))
    print(f"serve gateway defaults: policy={_sd.policy} (--policy "
          f"edf = deadline-aware admission, fair = weighted fair share "
          f"across tenants), classes {_classes} (request 'class' field), "
          f"tenant quota "
          f"{'unbounded' if not _sd.tenant_quota else _sd.tenant_quota} "
          f"(--tenant-quota), endpoints POST /v1/solve + "
          f"GET /v1/requests/<id> /healthz /metrics, POST /drainz "
          f"(graceful drain; overload answers 429 + Retry-After)")
    print(f"engine checkpoint: interval "
          f"{_sd.engine_ckpt_interval or 'off'} boundaries "
          f"(--engine-ckpt-interval N; always one at drain when on), "
          f"dir {_sd.engine_ckpt_dir or '<out-dir>/engine-ckpt'} "
          f"(--engine-ckpt-dir) — atomic generation manifests + per-lane "
          f"field files; serve --resume DIR continues in-flight lanes "
          f"bit-identically, re-queues waiting requests in policy order, "
          f"resumes usage billing from stamped partials; POST "
          f"/drainz?handoff=1 = drain-to-checkpoint (zero-downtime "
          f"handoff); corrupt manifests quarantine + fall back one "
          f"generation")

    # fleet serving (ISSUE 18): the pod-scale half — one router process
    # over N gateways; the dynamic story (placements, steals, lost
    # backends) lives on the router's /metrics and /statusz
    from .fleet.placement import (BURN_THRESHOLD as _burn_thr,
                                  POLICIES as _fleet_policies)

    print(f"fleet serving: heat-tpu fleet --backends host:port,... — "
          f"edge admission + placement over per-backend GET /v1/status "
          f"(policies {'|'.join(_fleet_policies)}; burn demotion at "
          f"fast&slow > {_burn_thr:g}, mega-capability routing), "
          f"health probes with retry-on-alternate, fleet-wide /metrics "
          f"/statusz /v1/usage, checkpoint-handoff work stealing "
          f"(--steal-threshold S; /drainz?handoff=1 -> POST /v1/resume "
          f"on the idlest backend, bit-identical); gate "
          f"benchmarks/fleet_lab.json")

    # fleet resilience (ISSUE 20): circuit breakers, deadline
    # propagation, hedged relay, brownout shedding
    from .fleet.resilience import Breaker as _Brk
    from .fleet.router import FleetConfig as _FCfg

    _fc = _FCfg()
    print(f"fleet resilience: per-backend circuit breakers (trip after "
          f"{_fc.breaker_trip} errors or {_fc.breaker_burn_ticks} burn "
          f"ticks, cooldown {_fc.breaker_cooldown_s:g}s doubling to "
          f"{_Brk.COOLDOWN_MAX_S:g}s; half-open re-admission via the "
          f"sine canary through the router path), retry budget "
          f"{_fc.retry_budget_cap:g} tokens +{_fc.retry_budget_ratio:g}"
          f"/success with jittered backoff (base "
          f"{_fc.retry_backoff_s:g}s), X-Deadline-Ms propagation "
          f"(edge-minted, decremented per hop; expired rows shed with "
          f"zero device steps), --hedge-factor F interactive hedging "
          f"(floor {_fc.hedge_floor_s:g}s, loser cancelled via POST "
          f"/v1/cancel), brownout sheds batch then standard when every "
          f"backend burns; gate benchmarks/fleet_resilience_lab.json")

    # invariant guard (ISSUE 11): the static-analysis suite's static
    # half — rule families, committed schema registry population, and
    # whether THIS process's locks were built with the dynamic
    # lock-order watchdog armed
    from .analysis import RULE_FAMILIES
    from .analysis.schema import load_registry
    from .runtime import debug as _debug

    _reg = load_registry(Path(__file__).resolve().parent / "analysis"
                         / "schemas" / "records.json")
    _nev = len((_reg or {}).get("events", {}))
    print(f"static analysis: {len(RULE_FAMILIES)} rule families "
          f"(heat-tpu check / make check: "
          f"{', '.join(sorted(RULE_FAMILIES))}), schema registry "
          f"{_nev} event(s)"
          + ("" if _reg else " — MISSING, run heat-tpu check "
             "--update-schemas") +
          f"; lock-order watchdog "
          f"{'ARMED' if _debug.lockcheck_enabled() else 'available'} "
          f"(HEAT_TPU_LOCKCHECK=1; order "
          + " < ".join(sorted(_debug.LOCK_RANKS,
                              key=_debug.LOCK_RANKS.get)) + ")")

    # race guard (ISSUE 14): the lockset analysis's committed guard map
    # and whether THIS process's thread-shared objects were built with
    # the dynamic race sanitizer armed
    from .analysis.races import load_guard_map

    _gmap = load_guard_map(Path(__file__).resolve().parent / "analysis"
                           / "schemas" / "guards.json")
    _nfld = len((_gmap or {}).get("fields", {}))
    print(f"race guard: guard map {_nfld} field(s)"
          + ("" if _gmap else " — MISSING, run heat-tpu check "
             "--update-schemas") +
          f"; race sanitizer "
          f"{'ARMED' if _debug.racecheck_enabled() else 'available'} "
          f"(HEAT_TPU_RACECHECK=1 raises, =record logs + flight-dumps)")

    # program auditor (ISSUE 13): the jaxpr-level half — registered
    # program families, committed digest population, and the declared
    # vs freshly-enumerated compile budget (enumeration is pure python
    # over ServeConfig, no tracing)
    from .analysis.programs import (default_registry_path,
                                    enumerate_step_keys,
                                    iter_program_specs)
    from .analysis.programs import load_registry as _load_digests

    _dreg = _load_digests(default_registry_path())
    _nfam = len(iter_program_specs())
    _declared = ((_dreg or {}).get("compile_budget") or {}).get(
        "max_programs")
    print(f"program audit: {_nfam} program families (heat-tpu audit: "
          f"donation, purity, dtype, budget, digests), digest registry "
          f"{len((_dreg or {}).get('programs', {}))} program(s)"
          + ("" if _dreg else " — MISSING, run heat-tpu audit "
             "--update-digests")
          + f"; compile budget declared={_declared} "
          f"enumerated={enumerate_step_keys()['total']}")

    # persistent compile cache: which programs are already warm (serve
    # buckets, backend advance programs, guard probes all land here) —
    # entry names are XLA key hashes, so report population, not keys
    import os

    from .utils.cache import default_cache_dir

    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or default_cache_dir()
    entries = []
    p = Path(cache_dir)
    if p.is_dir():
        entries = [e for e in p.iterdir() if e.is_file()]
    if entries:
        size_mib = sum(e.stat().st_size for e in entries) / 2**20
        print(f"compile cache: {cache_dir} — warm ({len(entries)} compiled "
              f"program(s), {size_mib:.1f} MiB); backends/serve buckets "
              f"compiled under this jax/platform skip their cold compile")
    else:
        print(f"compile cache: {cache_dir} — cold/empty (first run of each "
              f"backend chunk program or serve bucket pays its compile)")
    return 0


def cmd_calibrate(args) -> int:
    from .calibrate import run as calibrate_run

    rec = calibrate_run(args.out, quick=args.quick)
    return 0 if rec.get("fit_complete") else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return {"run": cmd_run, "viz": cmd_viz, "info": cmd_info,
            "launch": cmd_launch, "plan": cmd_plan, "serve": cmd_serve,
            "bench": cmd_bench, "calibrate": cmd_calibrate,
            "trace": cmd_trace, "usage": cmd_usage, "check": cmd_check,
            "fleet": cmd_fleet,
            "audit": cmd_audit,
            "perfcheck": cmd_perfcheck}[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
