"""heat_tpu — a TPU-native heat-equation framework.

A from-scratch JAX/XLA/Pallas/shard_map rebuild of the capability set of
``cssrikanth/CUDA-HIP-MPI-Heat-equation-test``: the 2D (and 3D) explicit
FTCS diffusion stencil, driven by the same ``input.dat`` contract, with the
reference's seven programming-model variants re-imagined as four pluggable
backends over one core:

- ``serial``  numpy oracle
- ``xla``     jit + fused slice stencil (compiler-generated kernel)
- ``pallas``  hand-written TPU kernel
- ``sharded`` shard_map + ppermute halo exchange over a device mesh

See SURVEY.md at the repo root for the reference analysis this build follows.
"""

from .backends import SolveResult, solve  # noqa: F401
from .config import VARIANTS, HeatConfig, parse_input, variant_config  # noqa: F401
from .grid import coords, initial_condition  # noqa: F401

__version__ = "0.1.0"
