"""Text dataset IO: the ``int.dat`` / ``soln.dat`` contract.

File format (fortran/serial/heat.f90:50-55, 77-83): one whitespace-separated
``x y T`` triplet per line (``x y z T`` quadruplet for the 3-D extension),
row-major — outer loop over the x index, inner over y — n^2 lines total.
The reference's viz scripts regex-split each line (fortran/serial/out.py:17-25),
so any whitespace/precision works; we write %.17g for f64 round-tripping.

MPI variants write one file per rank, ``soln#####.dat``, gated on the
``soln`` input flag (fortran/mpi+cuda/heat.F90:277-288); the sharded analog
here writes one file per *shard*, numbered by linear mesh index, so existing
reference post-processing habits carry over.

A C++ fast path (``native/fastio.cpp``, loaded via ctypes) accelerates the
O(n^2)-line text dump; numpy is the always-available fallback.
"""

from __future__ import annotations

import itertools
from pathlib import Path
from typing import Tuple

import numpy as np

from .native import fast_write_triplets


def _triplet_table(axes: Tuple[np.ndarray, ...], T: np.ndarray) -> np.ndarray:
    """Flatten coords+field into an (N, ndim+1) float64 table in file order."""
    grids = np.meshgrid(*axes, indexing="ij")
    cols = [g.reshape(-1) for g in grids] + [np.asarray(T, np.float64).reshape(-1)]
    return np.column_stack([np.asarray(c, np.float64) for c in cols])


def write_dat(path, axes: Tuple[np.ndarray, ...], T: np.ndarray) -> None:
    table = _triplet_table(axes, T)
    if not fast_write_triplets(str(path), table):
        with open(path, "w") as f:
            np.savetxt(f, table, fmt="%.17g")


def write_int_dat(path, axes, T0) -> None:
    """Pre-solve dump (fortran/serial/heat.f90:50-55)."""
    write_dat(path, axes, T0)


def write_soln(path, axes, T) -> None:
    """Post-solve dump (fortran/serial/heat.f90:77-83)."""
    write_dat(path, axes, T)


def write_soln_sharded(directory, axes, T_sharded, mesh, prefix: str = "soln") -> list:
    """Per-shard solution files ``soln#####.dat``
    (fortran/mpi+cuda/heat.F90:277-288). Each process writes only its
    addressable shards; shard number = linear index of its mesh coordinates."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    mesh_shape = mesh.devices.shape
    dev_to_coords = {}
    for coords in itertools.product(*[range(s) for s in mesh_shape]):
        dev_to_coords[mesh.devices[coords].id] = coords
    written = []
    for shard in T_sharded.addressable_shards:
        coords = dev_to_coords[shard.device.id]
        rank = int(np.ravel_multi_index(coords, mesh_shape))
        local = np.asarray(shard.data)
        local_axes = []
        for d, ax in enumerate(axes):
            npts = local.shape[d]
            start = coords[d] * npts
            local_axes.append(ax[start : start + npts])
        path = directory / f"{prefix}{rank:05d}.dat"
        write_dat(path, tuple(local_axes), local)
        written.append(path)
    return written


def write_soln_blocks(directory, axes, T: np.ndarray, mesh_shape,
                      prefix: str = "soln") -> list:
    """Per-shard solution files from the gathered host field: slice the
    global array back into its mesh blocks and write one ``soln#####.dat``
    per block — the single-process analog of the reference's per-rank dumps
    (fortran/mpi+cuda/heat.F90:277-288), rank = linear mesh index."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    written = []
    locals_per_dim = [T.shape[d] // mesh_shape[d] for d in range(len(mesh_shape))]
    for coords in itertools.product(*[range(s) for s in mesh_shape]):
        rank = int(np.ravel_multi_index(coords, mesh_shape))
        sl = tuple(
            slice(c * lp, (c + 1) * lp) for c, lp in zip(coords, locals_per_dim)
        )
        local_axes = tuple(ax[s] for ax, s in zip(axes, sl))
        path = directory / f"{prefix}{rank:05d}.dat"
        write_dat(path, local_axes, T[sl])
        written.append(path)
    return written


def read_dat(path, ndim: int = 2):
    """Read a .dat file back into (axes, T). Assumes the square row-major
    layout the writers produce (matches fortran/serial/out.py:27-36)."""
    table = np.loadtxt(path)
    ncols = table.shape[1]
    if ncols != ndim + 1:
        raise ValueError(f"{path}: expected {ndim + 1} columns, got {ncols}")
    npoints = table.shape[0]
    # infer the grid extents from the coordinate columns (blocks from a
    # rectangular decomposition need not be square)
    shape = tuple(len(np.unique(table[:, d])) for d in range(ndim))
    if int(np.prod(shape)) != npoints:
        raise ValueError(
            f"{path}: {npoints} lines inconsistent with inferred grid {shape}"
        )
    T = table[:, -1].reshape(shape)
    axes = []
    for d in range(ndim):
        col = table[:, d].reshape(shape)
        sl = [0] * ndim
        sl[d] = slice(None)
        axes.append(col[tuple(sl)])
    return tuple(axes), T
