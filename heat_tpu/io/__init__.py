from .datfiles import (  # noqa: F401
    read_dat,
    write_dat,
    write_int_dat,
    write_soln,
    write_soln_blocks,
    write_soln_sharded,
)
