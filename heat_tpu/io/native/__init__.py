"""ctypes binding to the native IO library, with transparent auto-build.

pybind11 is not available in this image; the CPython↔C++ boundary is plain
ctypes over an ``extern "C"`` surface, the same pattern the reference uses
for its Fortran↔C++ boundary (``bind(c)`` interface block,
fortran/hip/heat.F90:48-102). If ``libfastio.so`` is missing we try one
quiet ``make``; on any failure callers fall back to pure numpy.
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path
from typing import Optional

import numpy as np

_DIR = Path(__file__).parent
_SO = _DIR / "libfastio.so"
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not _SO.exists():
        try:
            subprocess.run(
                ["make", "-s"], cwd=_DIR, check=True,
                capture_output=True, timeout=120,
            )
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(str(_SO))
        lib.heat_write_table.restype = ctypes.c_int
        lib.heat_write_table.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
            ctypes.c_long,
            ctypes.c_long,
        ]
        lib.heat_read_table.restype = ctypes.c_long
        lib.heat_read_table.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
            ctypes.c_long,
        ]
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def native_available() -> bool:
    return _load() is not None


def fast_write_triplets(path: str, table: np.ndarray) -> bool:
    """Write an (N, k) float64 table as text lines. True iff native path ran."""
    lib = _load()
    if lib is None:
        return False
    table = np.ascontiguousarray(table, dtype=np.float64)
    rc = lib.heat_write_table(path.encode(), table, table.shape[0], table.shape[1])
    return rc == 0


def fast_read_values(path: str, max_vals: int) -> Optional[np.ndarray]:
    """Read whitespace-separated doubles. None if native lib unavailable."""
    lib = _load()
    if lib is None:
        return None
    out = np.empty(max_vals, dtype=np.float64)
    got = lib.heat_read_table(str(path).encode(), out, max_vals)
    if got < 0:
        raise FileNotFoundError(path)
    return out[:got]
