// Native IO helpers for the .dat text contract.
//
// The reference's only non-Fortran native component is its HIP kernel file
// (fortran/hip/heat_kernel.cpp); on TPU the kernels live in Pallas, so the
// native dimension of this framework sits where it still pays off: the
// O(n^2)-line text dumps of soln.dat/int.dat (fortran/serial/heat.f90:77-83),
// which dominate wall-clock at large n if written from Python. Compiled to
// libfastio.so and bound via ctypes (no pybind11 in the image).
//
// Format parity: whitespace-separated floating-point columns, one point per
// line, readable by the reference's regex-splitting viz scripts
// (fortran/serial/out.py:17-25).

#include <charconv>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>

namespace {
constexpr size_t kBufCap = 1 << 20;  // 1 MiB write buffer

struct Buf {
  FILE* f;
  std::unique_ptr<char[]> data{new char[kBufCap + 4096]};
  size_t len = 0;

  explicit Buf(FILE* file) : f(file) {}
  void flush() {
    if (len) {
      fwrite(data.get(), 1, len, f);
      len = 0;
    }
  }
  void put_double(double v) {
    auto [ptr, ec] = std::to_chars(data.get() + len, data.get() + len + 64, v);
    (void)ec;
    len = ptr - data.get();
  }
  void put_char(char c) { data[len++] = c; }
  void maybe_flush() {
    if (len >= kBufCap) flush();
  }
};
}  // namespace

extern "C" {

// Write `rows` lines of `cols` doubles each. Returns 0 on success.
int heat_write_table(const char* path, const double* data, long rows, long cols) {
  FILE* f = fopen(path, "w");
  if (!f) return -1;
  Buf buf(f);
  for (long i = 0; i < rows; ++i) {
    const double* row = data + i * cols;
    for (long j = 0; j < cols; ++j) {
      if (j) buf.put_char(' ');
      buf.put_double(row[j]);
      buf.maybe_flush();  // per value: the slack must bound ONE value,
                          // not a whole row of caller-chosen width
    }
    buf.put_char('\n');
    buf.maybe_flush();
  }
  buf.flush();
  int rc = ferror(f) ? -2 : 0;
  fclose(f);
  return rc;
}

// Read up to `max_vals` whitespace-separated doubles from a text file.
// Returns the number parsed, or -1 on open failure.
long heat_read_table(const char* path, double* out, long max_vals) {
  FILE* f = fopen(path, "r");
  if (!f) return -1;
  long count = 0;
  while (count < max_vals && fscanf(f, "%lf", &out[count]) == 1) ++count;
  fclose(f);
  return count;
}

}  // extern "C"
