"""Configuration: ``input.dat`` parsing and run options.

The reference drives every variant from a positional whitespace text file
``input.dat`` holding ``n sigma nu dom_len ntime`` (serial form, see
``fortran/serial/heat.f90:13``) with a sixth ``soln`` dump flag in the MPI
variants (``fortran/mpi+cuda/heat.F90:83``). Single-process variants silently
ignore a trailing sixth field, so one file drives every backend — this parser
preserves that contract (both arities accepted everywhere).

What the reference expresses as *compile-time* flags (``-DUSE_CUDA``,
``-DNO_AWARE`` in ``fortran/mpi+cuda/makefile:1-6``; ``SINGLE_PRECISION`` in
``fortran/hip/heat_kernel.cpp:5-9``) become *runtime* fields here: ``comm``
(direct vs host-staged halo exchange), ``dtype``, ``backend``.
"""

from __future__ import annotations

import dataclasses
import math
import re
from pathlib import Path
from typing import Optional, Tuple

_DTYPES = ("float64", "float32", "bfloat16")
_BACKENDS = ("serial", "xla", "pallas", "sharded")
_BCS = ("edges", "ghost", "periodic")
_ICS = ("hat", "hat_half", "hat_small", "uniform", "zero", "sine")
_COMMS = ("direct", "staged")
_ASYNC_IO = ("on", "off", "auto")
_ON_NAN = ("abort", "rollback")
_EXCHANGES = ("seq", "indep", "overlap")
_LOCAL_KERNELS = ("auto", "xla", "pallas")

# --serve-lane-kernel grammar (serve/scheduler.py ServeConfig.lane_kernel):
# the serving engine's chunk-program body per bucket. "auto" = the Pallas
# multi-lane kernels on TPU wherever the bucket has a kernel plan, the
# vmapped XLA stencil elsewhere; "pallas"/"xla" force it (an unavailable
# Pallas bucket under "pallas" degrades to XLA as a structured
# lane_kernel_fallback record + counter, never an error — the XLA lane
# program is the bit-exactness oracle either way).
LANE_KERNELS = ("auto", "pallas", "xla")


@dataclasses.dataclass(frozen=True)
class HeatConfig:
    """Full run configuration.

    The first six fields mirror ``input.dat`` exactly; the rest are framework
    options (runtime analogs of the reference's build-time variant choices).
    """

    # --- input.dat fields (fortran/serial/heat.f90:13, mpi+cuda/heat.F90:83)
    n: int = 256                # grid points per side
    sigma: float = 0.25         # CFL number
    nu: float = 0.05            # diffusivity
    dom_len: float = 2.0        # domain length
    ntime: int = 30             # number of timesteps
    soln: bool = False          # dump solution files at the end

    # --- framework options
    ndim: int = 2               # 2 -> 5-point stencil, 3 -> 7-point
    dtype: str = "float32"      # float64 parity / float32 / bfloat16(+f32 acc)
    backend: str = "xla"
    ic: str = "hat"             # initial condition preset (see grid.py)
    bc: str = "edges"           # "edges": frozen boundary cells (serial semantics)
                                # "ghost": Dirichlet-by-ghost ring (MPI semantics)
                                # "periodic": torus topology — the pbc=.true.
                                # the reference's mpi_cart_create is built for
                                # but never enables (mpi+cuda/heat.F90:76,97)
    bc_value: float = 1.0       # boundary temperature (unused for periodic)
    comm: str = "direct"        # halo exchange: direct ICI ppermute vs host-staged
    exchange: str = "indep"     # ghost-write formulation: "indep" (all ghost
                                # writes independent — one fewer full-shard
                                # copy per exchange in the compiled multi-
                                # device advance) vs "seq" (axes chained, the
                                # reference-like form) vs "overlap" (indep
                                # exchange + interior compute issued while
                                # halo slabs are in flight; Pallas local
                                # kernel only). Bit-identical results; see
                                # parallel/halo.py::halo_exchange_indep and
                                # backends/sharded.py padded_multi_overlap
    local_kernel: str = "auto"  # sharded per-shard compute: auto (pallas on
                                # TPU, xla elsewhere), or forced
    mesh_shape: Optional[Tuple[int, ...]] = None  # device mesh; None = auto
    heartbeat_every: int = 0    # print "time_it: i" every k steps (0 = off)
    write_int: bool = False     # dump the initial field to int.dat pre-solve
                                # (the single-process reference variants do
                                # this unconditionally,
                                # fortran/serial/heat.f90:50-55 — their
                                # presets below turn it on)
    report_sum: bool = False    # global temperature sum (the reference's
                                # commented-out MPI_Reduce, mpi+cuda/heat.F90:266-273)
    checkpoint_every: int = 0   # periodic snapshot interval (0 = off)
    checkpoint_dir: str = "checkpoints"
    async_io: str = "auto"      # checkpoint/numerics I/O pipeline: "on" =
                                # snapshot-and-continue (one device-side
                                # buffer copy at the boundary; D2H + disk
                                # write in a background thread, bounded
                                # queue), "off" = the reference-shaped
                                # sync path (device idles through fetch +
                                # write), "auto" = on (the hook for a
                                # future platform heuristic; see
                                # use_async_io)
    profile_dir: Optional[str] = None  # jax.profiler trace output dir
    check_numerics: bool = False  # per-chunk NaN/Inf detection (debug mode)
    on_nan: str = "abort"       # non-finite response under check_numerics:
                                # "abort" raises at the flagged step (the
                                # original contract); "rollback" restores
                                # the last boundary whose finite flag
                                # PASSED and re-steps — transient soft
                                # errors (or injected NaN) recover, while a
                                # deterministic blow-up re-flags at the
                                # same step and aborts after a bounded
                                # number of retries (backends/common.py)
    inject: str = ""            # deterministic fault-injection spec
                                # (runtime/faults.py grammar:
                                # "crash@N[:proc=P]", "nan@N",
                                # "ckpt-corrupt@N", "ckpt-truncate@N",
                                # "sink-error@N[:times=K]",
                                # "sink-slow:ms=M", comma-separated).
                                # Empty (the default) = no fault layer at
                                # all; HEAT_TPU_FAULTS env var is the
                                # worker-process channel
    fuse_steps: int = 0         # pallas temporal blocking: FTCS steps fused
                                # per kernel pass (0 = auto, 1 = off)
    parity_order: bool = False  # literal update-then-swap step ordering
                                # (mpi+cuda/heat.F90:209-218): sharded-only
                                # bit-parity mode carrying the ghost ring as
                                # state; see backends/sharded.py

    def __post_init__(self):
        if self.n < 3:
            raise ValueError(f"grid size n must be >= 3, got {self.n}")
        if self.ntime < 0:
            raise ValueError(f"ntime must be >= 0, got {self.ntime}")
        if self.ndim not in (2, 3):
            raise ValueError(f"ndim must be 2 or 3, got {self.ndim}")
        if self.dtype not in _DTYPES:
            raise ValueError(f"dtype must be one of {_DTYPES}, got {self.dtype!r}")
        if self.backend not in _BACKENDS:
            raise ValueError(f"backend must be one of {_BACKENDS}, got {self.backend!r}")
        if self.bc not in _BCS:
            raise ValueError(f"bc must be one of {_BCS}, got {self.bc!r}")
        if self.ic not in _ICS:
            raise ValueError(f"ic must be one of {_ICS}, got {self.ic!r}")
        if self.comm not in _COMMS:
            raise ValueError(f"comm must be one of {_COMMS}, got {self.comm!r}")
        if self.exchange not in _EXCHANGES:
            raise ValueError(
                f"exchange must be one of {_EXCHANGES}, got {self.exchange!r}")
        if self.local_kernel not in _LOCAL_KERNELS:
            raise ValueError(
                f"local_kernel must be one of {_LOCAL_KERNELS}, got {self.local_kernel!r}")
        # FTCS stability wants sigma <= 1/(2*ndim); allow mildly unstable
        # experiments but reject nonsense outright, in every dimension.
        if self.sigma <= 0 or self.sigma > 10:
            raise ValueError(f"sigma out of range: {self.sigma}")
        if self.fuse_steps < 0:
            raise ValueError(f"fuse_steps must be >= 0, got {self.fuse_steps}")
        if self.async_io not in _ASYNC_IO:
            raise ValueError(
                f"async_io must be one of {_ASYNC_IO}, got {self.async_io!r}")
        if self.on_nan not in _ON_NAN:
            raise ValueError(
                f"on_nan must be one of {_ON_NAN}, got {self.on_nan!r}")
        if self.on_nan == "rollback" and not self.check_numerics:
            raise ValueError(
                "on_nan='rollback' requires check_numerics=True — the "
                "finite flag at each boundary is the rollback trigger")
        if self.inject:
            # fail at parse time, not at step N of a long solve (lazy import:
            # the common inject="" path must not load the fault layer at all)
            from .runtime.faults import parse_spec

            parse_spec(self.inject)

    # --- derived quantities (fortran/serial/heat.f90:15-17,59) -------------
    @property
    def delta(self) -> float:
        """Grid spacing: dom_len / (n - 1)."""
        return self.dom_len / (self.n - 1)

    @property
    def dt(self) -> float:
        """Timestep from the CFL condition: sigma * delta^2 / nu."""
        return (self.sigma * self.delta**2) / self.nu

    @property
    def r(self) -> float:
        """Stencil coefficient nu*dt/delta^2.

        Algebraically identical to ``sigma`` (the dt substitution cancels);
        the reference still derives it through dt (fortran/serial/heat.f90:59)
        and so do we, keeping the full chain for config parity.
        """
        return (self.nu * self.dt) / self.delta**2

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.n,) * self.ndim

    @property
    def points(self) -> int:
        return self.n**self.ndim

    def use_async_io(self) -> bool:
        """Resolve the ``async_io`` knob to a verdict for this run.

        "auto" resolves to ON: the on-loop cost of the async pipeline is
        one device-side buffer copy per boundary (microseconds at HBM
        bandwidth) against the seconds-scale D2H+write it takes off the
        critical path, so there is no measured regime where sync wins.
        The tri-state exists so a platform heuristic can demote auto later
        without repurposing the explicit values: "off" stays the
        bit-faithful reference-shaped fallback (and the A/B baseline for
        benchmarks/ckpt_overlap.py), "on" stays a user promise. The serial
        backend ignores the knob (host-resident field — there is no D2H to
        hide)."""
        return self.async_io != "off"

    def with_(self, **kw) -> "HeatConfig":
        return dataclasses.replace(self, **kw)


def parse_input(path: str | Path) -> HeatConfig:
    """Parse an ``input.dat`` file (5- or 6-field form).

    Field order: ``n sigma nu dom_len ntime [soln]`` — README.md:7 and
    ``fortran/mpi+cuda/heat.F90:81-85``. Tokens may span multiple lines
    (Fortran list-directed reads don't care); extra trailing tokens beyond
    six are ignored, like the serial variant ignores the ``soln`` flag.
    """
    text = Path(path).read_text()
    toks = re.split(r"\s+", text.strip())
    if len(toks) < 5:
        raise ValueError(
            f"{path}: expected at least 5 fields 'n sigma nu dom_len ntime', got {toks}"
        )
    n = int(toks[0])
    sigma = float(toks[1])
    nu = float(toks[2])
    dom_len = float(toks[3])
    ntime = int(toks[4])
    soln = bool(int(toks[5])) if len(toks) >= 6 else False
    return HeatConfig(n=n, sigma=sigma, nu=nu, dom_len=dom_len, ntime=ntime, soln=soln)


# Request-JSONL surface of the serving engine (serve/api.py): the physics
# and per-request knobs a tenant may set. Framework-level execution knobs
# (backend, mesh, checkpointing, async_io) are engine policy, not request
# payload — a request naming them is a typo or a privilege confusion, and
# both must reject loudly rather than silently serve different physics.
_REQUEST_KEYS = ("n", "sigma", "nu", "dom_len", "ntime", "ndim", "dtype",
                 "ic", "bc", "bc_value", "inject")

# Request keys the SCHEDULER owns (never part of the physics config):
# "id" names the record, "deadline_ms" bounds the request's wall time from
# submission (overriding the engine-default --serve-deadline), "tenant"
# names the submitting tenant (fair-share accounting + per-tenant quotas),
# "class" picks the SLO class, and "until"/"tol" pick the completion
# semantics (fixed step count vs steady-state early exit) — see
# serve/scheduler.py + serve/policy.py.
_SCHEDULER_KEYS = ("id", "deadline_ms", "tenant", "class", "until", "tol")

# SLO classes of the serving front-end, name -> admission priority (lower
# is more urgent). The class is a *scheduler* field: it shapes admission
# order (serve/policy.py edf/fair policies) and labels the /metrics
# latency histograms; it never reaches the physics. Defined here because
# this module is the one validation chokepoint for request payloads —
# JSONL (serve/api.py) and HTTP (serve/gateway.py) both funnel through
# validate_slo_fields, so a typoed class can never silently serve at the
# wrong tier.
SLO_CLASSES = {"interactive": 0, "standard": 1, "batch": 2}
DEFAULT_SLO_CLASS = "standard"
DEFAULT_TENANT = "default"

# Default per-class SLO targets (deadline-hit fraction) for the burn-rate
# monitor (runtime/prof.py BurnMonitor): the error budget a class may
# spend is 1 - target, and the monitor's burn rate is miss_fraction /
# budget. Tighter classes get tighter budgets; override per engine with
# ``--slo-targets interactive=0.999`` (parse_slo_targets below). Lives
# here with SLO_CLASSES because this module is the one validation
# chokepoint for anything class-shaped.
SLO_TARGETS = {"interactive": 0.99, "standard": 0.95, "batch": 0.9}

_TENANT_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


def validate_slo_fields(tenant, slo_class) -> Tuple[str, str]:
    """Validate (and default) a request's tenant/class pair.

    Raised errors are per-request rejections at both front doors (JSONL
    parse, HTTP admission) — the same loud-typo contract as
    config_from_request's unknown-key check."""
    tenant = DEFAULT_TENANT if tenant is None else str(tenant)
    if not _TENANT_RE.match(tenant):
        raise ValueError(
            f"tenant must match {_TENANT_RE.pattern} (1-64 chars of "
            f"[A-Za-z0-9._-]), got {tenant!r}")
    slo_class = DEFAULT_SLO_CLASS if slo_class is None else str(slo_class)
    if slo_class not in SLO_CLASSES:
        raise ValueError(
            f"class must be one of {sorted(SLO_CLASSES)} (priority order "
            f"{sorted(SLO_CLASSES, key=SLO_CLASSES.get)}), got {slo_class!r}")
    return tenant, slo_class


# Completion semantics of a request (semantic scheduling, ISSUE 16):
# "steps" runs exactly ntime steps (the default, bit-for-bit the historic
# behavior); "steady" retires the lane at the first chunk boundary whose
# residual EWMA passes the steady tolerance (per-request "tol", else the
# engine-wide --steady-tol), with ntime as the hard cap. Defined here
# because this module is the one validation chokepoint for request
# payloads — JSONL (serve/api.py) and HTTP (serve/gateway.py) both funnel
# through validate_until_fields.
UNTIL_MODES = ("steps", "steady")
DEFAULT_UNTIL = "steps"


def validate_until_fields(until, tol) -> Tuple[str, Optional[float]]:
    """Validate (and default) a request's until/tol pair.

    ``tol`` is only meaningful with ``until=steady``; supplying it on a
    fixed-step request is rejected loudly (same loud-typo contract as
    validate_slo_fields — a tenant who set ``tol`` expected early exit,
    and silently running all steps would serve different semantics)."""
    until = DEFAULT_UNTIL if until is None else str(until)
    if until not in UNTIL_MODES:
        raise ValueError(
            f"until must be one of {list(UNTIL_MODES)}, got {until!r}")
    if tol is not None:
        if until != "steady":
            raise ValueError(
                f"tol is only valid with until='steady', got until={until!r}")
        try:
            tol = float(tol)
        except (TypeError, ValueError):
            raise ValueError(f"tol must be a positive number, got {tol!r}")
        if not (tol > 0.0) or not math.isfinite(tol):
            raise ValueError(f"tol must be a positive finite number, "
                             f"got {tol!r}")
    return until, tol


def parse_listen(s) -> Tuple[str, int]:
    """``--listen HOST:PORT`` grammar: ':0' / '0' pick an ephemeral port,
    a bare port listens on 127.0.0.1 (the gateway is a front-end, not an
    exposed-by-default service)."""
    text = str(s).strip()
    host, sep, port_s = text.rpartition(":")
    if not sep:
        host, port_s = "", text
    host = host or "127.0.0.1"
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(
            f"--listen must be HOST:PORT (port an integer), got {s!r}"
        ) from None
    if not 0 <= port <= 65535:
        raise ValueError(f"--listen port must be in [0, 65535], got {port}")
    return host, port


def parse_tenant_weights(s) -> Tuple[Tuple[str, float], ...]:
    """``--tenant-weights a=4,b=1`` -> (("a", 4.0), ("b", 1.0)). Unlisted
    tenants weigh 1.0 (serve/policy.py FairShareQueue)."""
    out = []
    for tok in str(s).split(","):
        tok = tok.strip()
        if not tok:
            continue
        name, sep, w = tok.partition("=")
        if not sep:
            raise ValueError(
                f"--tenant-weights entries must be NAME=WEIGHT, got {tok!r}")
        tenant, _ = validate_slo_fields(name.strip(), None)
        try:
            weight = float(w)
        except ValueError:
            raise ValueError(
                f"--tenant-weights weight must be a number, got {w!r}"
            ) from None
        if not weight > 0:
            raise ValueError(
                f"--tenant-weights weight must be > 0, got {weight}")
        out.append((tenant, weight))
    return tuple(out)


def parse_slo_targets(s) -> Tuple[Tuple[str, float], ...]:
    """``--slo-targets interactive=0.999,batch=0.8`` -> (("interactive",
    0.999), ("batch", 0.8)). Classes must exist (SLO_CLASSES) and targets
    lie strictly in (0, 1) — a target of 1.0 is a zero error budget and
    every burn rate would be infinite; unlisted classes keep the
    SLO_TARGETS defaults."""
    out = []
    for tok in str(s).split(","):
        tok = tok.strip()
        if not tok:
            continue
        name, sep, t = tok.partition("=")
        if not sep:
            raise ValueError(
                f"--slo-targets entries must be CLASS=TARGET, got {tok!r}")
        name = name.strip()
        if name not in SLO_CLASSES:
            raise ValueError(
                f"--slo-targets class must be one of {sorted(SLO_CLASSES)}, "
                f"got {name!r}")
        try:
            target = float(t)
        except ValueError:
            raise ValueError(
                f"--slo-targets target must be a number, got {t!r}"
            ) from None
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"--slo-targets target must be in (0, 1), got {target}")
        out.append((name, target))
    return tuple(out)


def parse_on_off(v, flag: str) -> bool:
    """``on``/``off`` CLI grammar shared by boolean serve knobs
    (``--prof``)."""
    s = str(v).strip().lower()
    if s == "on":
        return True
    if s == "off":
        return False
    raise ValueError(f"{flag} must be 'on' or 'off', got {v!r}")


def parse_dispatch_depth(v) -> int:
    """``--dispatch-depth`` grammar (serve CLI): ``on`` -> 2 (the default
    pipeline: inspect chunk i's boundary while chunk i+1 computes),
    ``off`` -> 0 (fully synchronous debugging fallback — fence every
    boundary, extract on the scheduler thread), an integer N >= 1 -> keep
    N chunk programs in flight per bucket group. Deeper pipelines only
    help when boundary bookkeeping outlasts a whole chunk; each extra
    level delays lane swaps by one chunk, so 2 is almost always right."""
    s = str(v).strip().lower()
    if s == "on":
        return 2
    if s == "off":
        return 0
    try:
        n = int(s)
    except ValueError:
        raise ValueError(
            f"--dispatch-depth must be 'on', 'off', or an integer >= 1, "
            f"got {v!r}") from None
    if n < 1:
        raise ValueError(
            f"--dispatch-depth integer form must be >= 1 (use 'off' for "
            f"the synchronous fallback), got {n}")
    return n


def parse_mega_lanes(v) -> Optional[int]:
    """``--mega-lanes`` grammar (serve CLI): ``auto`` (default) -> None,
    resolved by the engine to 1 on a multi-device host and 0 on a
    single-device one; an integer N >= 0 pins the concurrent mega-lane
    budget (0 = bucket overflow stays a rejection, the pre-mega
    behavior, bit-identically)."""
    s = str(v).strip().lower()
    if s == "auto":
        return None
    try:
        n = int(s)
    except ValueError:
        raise ValueError(
            f"--mega-lanes must be 'auto' or an integer >= 0, got {v!r}"
        ) from None
    if n < 0:
        raise ValueError(f"--mega-lanes must be >= 0, got {n}")
    return n


def config_from_request(d) -> HeatConfig:
    """Build a HeatConfig from one parsed serve-request object.

    ``id`` and ``deadline_ms`` are the scheduler's (_SCHEDULER_KEYS),
    everything else must be a known request key; HeatConfig's own
    __post_init__ then validates values exactly as it does for the CLI,
    so a request cannot express a config the solo path would reject.
    """
    unknown = set(d) - set(_REQUEST_KEYS) - set(_SCHEDULER_KEYS)
    if unknown:
        raise ValueError(
            f"unknown request key(s) {sorted(unknown)}; allowed: "
            f"{sorted(_REQUEST_KEYS)} (+ optional {sorted(_SCHEDULER_KEYS)})")
    kw = {k: d[k] for k in _REQUEST_KEYS if k in d}
    # JSON numbers arrive untyped: pin the integer fields (a float n would
    # sail through range validation and break shapes much later)
    for k in ("n", "ntime", "ndim"):
        if k in kw:
            kw[k] = int(kw[k])
    for k in ("sigma", "nu", "dom_len", "bc_value"):
        if k in kw:
            kw[k] = float(kw[k])
    return HeatConfig(**kw)


def write_input(cfg: HeatConfig, path: str | Path) -> None:
    """Write the 6-field ``input.dat`` form (readable by every variant)."""
    # repr keeps full precision: a write/parse round-trip must not perturb
    # the physics (dt, r, checkpoint fingerprints).
    Path(path).write_text(
        f"{cfg.n} {cfg.sigma!r} {cfg.nu!r} {cfg.dom_len!r} {cfg.ntime} {int(cfg.soln)}\n"
    )


# Named presets reproducing each reference variant's semantics, so a user of
# the reference can select their variant by name (see SURVEY.md quirk #1: the
# IC/BC families differ silently between variants).
VARIANTS = {
    # Default-behavior parity (not just IC/BC): every Fortran single-process
    # variant writes int.dat unconditionally before solving
    # (fortran/serial/heat.f90:50-55, cuda_kernel/heat.F90:107-112,
    # cuda_cuf/heat.F90:94) and prints "time_it:" every step (serial :62,
    # cuda_kernel :31, cuda_cuf :29); the MPI variants heartbeat
    # master-gated without an int.dat (mpi+cuda/heat.F90:207,
    # hip/heat.F90:241); the python variants do neither. Opt out with
    # ``--no-write-int`` / ``--heartbeat-every 0``.
    #
    # fortran/serial/heat.f90: hat IC on [0.5,1.5]^2, frozen boundary cells
    "serial": dict(ic="hat", bc="edges", backend="serial", dtype="float64",
                   write_int=True, heartbeat_every=1),
    # fortran/cuda_kernel/heat.F90:99: hat with y in [0.5,1.0].
    # NOTE: f64 bit-parity implies the XLA step — the hand-written Pallas
    # kernel has no f64 (no f64 on the TPU VPU), so the pallas backend
    # transparently falls back. Run with --dtype float32 to exercise the
    # hand-written kernel itself (contract-tested in tests/test_config.py).
    "cuda_kernel": dict(ic="hat_half", bc="edges", backend="pallas", dtype="float64",
                        write_int=True, heartbeat_every=1),
    "cuda_managed": dict(ic="hat_half", bc="edges", backend="pallas", dtype="float64",
                         write_int=True, heartbeat_every=1),
    # fortran/cuda_cuf/heat.F90:86: same IC family, compiler-generated kernels
    "cuda_cuf": dict(ic="hat_half", bc="edges", backend="xla", dtype="float64",
                     write_int=True, heartbeat_every=1),
    # fortran/mpi+cuda/heat.F90:243-251: uniform 2.0, Dirichlet-by-ghost walls
    "mpi_cuda": dict(ic="uniform", bc="ghost", backend="sharded", comm="direct",
                     dtype="float64", heartbeat_every=1),
    # same but the staged (NO_AWARE) communication path, makefile:3-4
    "mpi_cuda_na": dict(ic="uniform", bc="ghost", backend="sharded", comm="staged",
                        dtype="float64", heartbeat_every=1),
    # fortran/hip/heat.F90: always-staged swap
    "hip": dict(ic="uniform", bc="ghost", backend="sharded", comm="staged",
                dtype="float64", heartbeat_every=1),
    # python/serial/heat.py: hat on [0.5,1.0]^2 w/ per-step edge reassert == edges BC
    # (no int.dat, no time_it heartbeat — the python variants print/plot only)
    "python_serial": dict(ic="hat_small", bc="edges", backend="serial", dtype="float64"),
    # python/cuda/cuda.py: throughput benchmark (IC no-op bug not replicated;
    # uniform field benchmarks identically)
    "python_cuda": dict(ic="uniform", bc="edges", backend="pallas", dtype="float32"),
}


def variant_config(name: str, base: Optional[HeatConfig] = None) -> HeatConfig:
    if name not in VARIANTS:
        raise KeyError(f"unknown variant {name!r}; choose from {sorted(VARIANTS)}")
    base = base or HeatConfig()
    return base.with_(**VARIANTS[name])
