"""Headline benchmark core: grid-points/sec/chip on the f32 Pallas stencil.

One measurement definition, two front doors: the repo-root ``bench.py``
(the driver-run artifact — supervised subprocess, retry, one JSON line)
and the ``heat-tpu bench`` CLI subcommand (inline, interactive). Both
report the overhead-corrected two-point rate with the raw single-call
rate alongside (``runtime/timing.py::two_point_rate``).

The shape mirrors the reference's single-GPU benchmark
(python/cuda/cuda.py:31-33: 4096^2, 10k steps; 8192 steps here has the
identical steady-state per-step cost), and ``vs_baseline`` is against the
ideal one-pass-per-step HBM roofline on this chip class (819 GB/s v5e /
2*itemsize = 1.024e11 points/s f32) — the bound no
one-kernel-launch-per-step design (the reference's structure) can exceed.
"""

from __future__ import annotations

N = 4096
STEPS = 8192
REPEATS = 3


def metric_name(n: int = N) -> str:
    return f"grid_points_per_sec_per_chip_{n}x{n}_f32_pallas"


def headline_measure(n: int = N, steps: int = STEPS,
                     repeats: int = REPEATS) -> dict:
    """Run the headline measurement on the current default platform and
    return the result record (the dict ``bench.py`` prints as JSON)."""
    import jax
    import jax.numpy as jnp

    from .backends.pallas import make_advance
    from .config import HeatConfig
    from .grid import initial_condition
    from .runtime.timing import two_point_rate

    platform = jax.default_backend()  # first device touch; may raise/hang

    cfg = HeatConfig(n=n, ntime=steps, dtype="float32", ic="hat",
                     backend="pallas")
    T0 = initial_condition(cfg).astype("float32")
    advance = make_advance(cfg)

    x = jax.device_put(jnp.asarray(T0))
    compiled = advance.lower(x, steps).compile()
    # advance donates its input, so two_point_rate recycles one buffer pair
    pts_per_s, raw = two_point_rate(compiled, x, n * n * steps,
                                    repeats=repeats)
    from . import machine

    chip = machine.current()
    return {
        "metric": metric_name(n),
        "value": pts_per_s,
        "unit": "points/s",
        "vs_baseline": pts_per_s / chip.roofline_points_per_s("float32"),
        "raw_single_call": raw,
        "platform": platform,
        # which chip class's one-pass HBM roofline vs_baseline divides by —
        # "(uncalibrated)" = spec-derived table entry, not a fitted one
        "baseline_chip": chip.label,
    }
