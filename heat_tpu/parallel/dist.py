"""Multi-host initialization.

The TPU-native analog of the reference's MPI world setup
(``mpi_init``/``comm_rank``/``comm_size`` + per-node GPU binding,
fortran/mpi+cuda/heat.F90:60-70): ``jax.distributed.initialize`` joins this
process to the job; device binding is owned by the JAX runtime (no
``cudaSetDevice`` analog needed). After initialization, ``jax.devices()``
spans the whole job and the mesh/halo machinery works unchanged — shard_map
collectives ride ICI within a slice and DCN across slices.

On a single host this is a no-op; call ``init_distributed()`` early (before
any backend use) when launching one process per host.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..runtime.logging import get_logger

_log = get_logger("heat_tpu.dist")


def _pod_env() -> bool:
    """Whether the environment looks like a multi-worker TPU pod — checked
    WITHOUT backend initialization (unlike jax.default_backend()). A
    single-hostname TPU_WORKER_HOSTNAMES is a one-worker job (the tunneled
    single-chip platform sets 'localhost'): nothing to join."""
    if os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()]) > 1


def _already_joined() -> bool:
    """Whether jax.distributed.initialize already ran — checked WITHOUT
    touching the XLA backend (jax.process_count() would initialize it, and
    initialize() raises once backends exist)."""
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:  # pragma: no cover - jax internals moved
        return False


def _enable_cpu_collectives() -> None:
    """Give a multi-process CPU world a real collectives implementation.

    Without one, jaxlib's CPU client rejects EVERY cross-process program —
    "Multiprocess computations aren't implemented on the CPU backend" — so
    the reference's mpirun-analog development mode (``heat-tpu launch``)
    could join a world but never compute in it: the sharded IC build, the
    halo exchange, and the shard-checkpoint resume all died at their first
    jit. jaxlib ships a gloo TCP implementation (the flag default is
    'none'); select it here, before the first backend client is created.
    Only fires when the run is pinned to CPU (the launch/worker path); TPU
    pods keep their native ICI/DCN collectives. Respects an explicit user
    override via JAX_CPU_COLLECTIVES_IMPLEMENTATION; older jax with no such
    knob keeps the status quo."""
    if os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION"):
        return  # user already chose (the flag machinery read the env var)
    on_cpu = (os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
              or (getattr(jax.config, "jax_platforms", None) or ""
                  ).startswith("cpu"))
    if not on_cpu:
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        _log.info("multi-process CPU world: gloo collectives enabled")
    except Exception:  # pragma: no cover - pre-gloo jaxlib
        _log.info("this jaxlib has no CPU collectives implementation; "
                  "cross-process CPU programs will not compile")


def cpu_collectives_info() -> dict:
    """Observability for the gloo unbreak (``_enable_cpu_collectives``):
    whether this jaxlib HAS a CPU collectives knob at all, what it is
    currently set to, and whether the user pinned it via env var — so
    ``heat-tpu info`` can say up front whether a multi-process CPU world
    (``heat-tpu launch``) will be able to compile cross-process programs,
    instead of that surfacing as a launch failure minutes in."""
    env = os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION") or None
    try:
        value = jax.config.read("jax_cpu_collectives_implementation")
        available = True
    except Exception:  # pre-gloo jaxlib: no such config option
        value, available = None, False
    return {
        "available": available,     # the knob (and gloo impl) exists
        "value": value,             # current selection ('none' until the
                                    # launch path or the user picks gloo)
        "env_override": env,        # user pinned it; launch won't touch it
    }


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join a multi-process JAX job (env-var driven when args are None).

    Mirrors ``jax.distributed.initialize`` semantics: on TPU pods with no
    args it auto-discovers from the runtime environment; elsewhere pass the
    coordinator address and process ids (or set JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID).

    MUST run before anything initializes the XLA backend (it is the first
    act of ``cmd_run`` for the sharded backend, as ``mpi_init`` is the
    first act of ``program heat``) — so the no-op decision below reads only
    environment state, never ``jax.process_count()``/``jax.devices()``.
    """
    if _already_joined():
        return
    explicit = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if explicit is None and not _pod_env():
        _log.info("single-process run (no coordinator configured)")
        return
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    _enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=explicit,  # None on a pod: runtime auto-discovers
        num_processes=num_processes,
        process_id=process_id,
    )
    _log.info(
        "joined distributed job: process %d/%d, %d local of %d global devices",
        jax.process_index(), jax.process_count(),
        len(jax.local_devices()), len(jax.devices()),
    )


def is_master() -> bool:
    return jax.process_index() == 0
