"""Multi-host initialization.

The TPU-native analog of the reference's MPI world setup
(``mpi_init``/``comm_rank``/``comm_size`` + per-node GPU binding,
fortran/mpi+cuda/heat.F90:60-70): ``jax.distributed.initialize`` joins this
process to the job; device binding is owned by the JAX runtime (no
``cudaSetDevice`` analog needed). After initialization, ``jax.devices()``
spans the whole job and the mesh/halo machinery works unchanged — shard_map
collectives ride ICI within a slice and DCN across slices.

On a single host this is a no-op; call ``init_distributed()`` early (before
any backend use) when launching one process per host.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..runtime.logging import get_logger

_log = get_logger("heat_tpu.dist")


def _pod_env() -> bool:
    """Whether the environment looks like a multi-worker TPU pod — checked
    WITHOUT backend initialization (unlike jax.default_backend()). A
    single-hostname TPU_WORKER_HOSTNAMES is a one-worker job (the tunneled
    single-chip platform sets 'localhost'): nothing to join."""
    if os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()]) > 1


def _already_joined() -> bool:
    """Whether jax.distributed.initialize already ran — checked WITHOUT
    touching the XLA backend (jax.process_count() would initialize it, and
    initialize() raises once backends exist)."""
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:  # pragma: no cover - jax internals moved
        return False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join a multi-process JAX job (env-var driven when args are None).

    Mirrors ``jax.distributed.initialize`` semantics: on TPU pods with no
    args it auto-discovers from the runtime environment; elsewhere pass the
    coordinator address and process ids (or set JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID).

    MUST run before anything initializes the XLA backend (it is the first
    act of ``cmd_run`` for the sharded backend, as ``mpi_init`` is the
    first act of ``program heat``) — so the no-op decision below reads only
    environment state, never ``jax.process_count()``/``jax.devices()``.
    """
    if _already_joined():
        return
    explicit = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if explicit is None and not _pod_env():
        _log.info("single-process run (no coordinator configured)")
        return
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    jax.distributed.initialize(
        coordinator_address=explicit,  # None on a pod: runtime auto-discovers
        num_processes=num_processes,
        process_id=process_id,
    )
    _log.info(
        "joined distributed job: process %d/%d, %d local of %d global devices",
        jax.process_index(), jax.process_count(),
        len(jax.local_devices()), len(jax.devices()),
    )


def is_master() -> bool:
    return jax.process_index() == 0
