"""Multi-host initialization.

The TPU-native analog of the reference's MPI world setup
(``mpi_init``/``comm_rank``/``comm_size`` + per-node GPU binding,
fortran/mpi+cuda/heat.F90:60-70): ``jax.distributed.initialize`` joins this
process to the job; device binding is owned by the JAX runtime (no
``cudaSetDevice`` analog needed). After initialization, ``jax.devices()``
spans the whole job and the mesh/halo machinery works unchanged — shard_map
collectives ride ICI within a slice and DCN across slices.

On a single host this is a no-op; call ``init_distributed()`` early (before
any backend use) when launching one process per host.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..runtime.logging import get_logger

_log = get_logger("heat_tpu.dist")


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join a multi-process JAX job (env-var driven when args are None).

    Mirrors ``jax.distributed.initialize`` semantics: on TPU pods with no
    args it auto-discovers from the runtime environment; elsewhere pass the
    coordinator address and process ids (or set JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID).
    """
    if jax.process_count() > 1:
        return  # already initialized
    explicit = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if explicit is None and jax.default_backend() != "tpu":
        _log.info("single-process run (no coordinator configured)")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _log.info(
        "joined distributed job: process %d/%d, %d local of %d global devices",
        jax.process_index(), jax.process_count(),
        len(jax.local_devices()), len(jax.devices()),
    )


def is_master() -> bool:
    return jax.process_index() == 0
