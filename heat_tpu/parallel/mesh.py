"""Device mesh construction — the cartesian-topology layer.

TPU-native replacement for the reference's MPI topology setup
(``fortran/mpi+cuda/heat.F90:87-103``): ``MPI_Dims_create`` becomes a
balanced factorization of the device count over the spatial axes,
``mpi_cart_create``/``cart_shift`` become a named ``jax.sharding.Mesh`` whose
axes the halo exchange addresses by name. Rank→GPU binding
(``cudaSetDevice`` by shared-node rank, :64-70) has no analog: the JAX
runtime owns device placement.

The reference decomposes only x (``ndims=1``, :28); here every spatial axis
is a mesh axis by default (the 2-D 4x4 decomposition targeted by
BASELINE.json), and a 1-D parity layout is just ``mesh_shape=(N, 1)``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

MESH_AXES = ("x", "y", "z")


def auto_mesh_shape(ndev: int, ndim: int) -> Tuple[int, ...]:
    """Balanced factorization of ``ndev`` into ``ndim`` factors, largest
    factors on the leading (most-contiguous) axes — MPI_Dims_create semantics
    (fortran/mpi+cuda/heat.F90:87-90)."""
    factors = [1] * ndim
    remaining = ndev
    # greedy: repeatedly give the smallest prime factor to the smallest axis
    primes = []
    k = 2
    while remaining > 1:
        while remaining % k == 0:
            primes.append(k)
            remaining //= k
        k += 1
    for p in sorted(primes, reverse=True):
        i = int(np.argmin(factors))
        factors[i] *= p
    return tuple(sorted(factors, reverse=True))


def build_mesh(
    ndim: int,
    mesh_shape: Optional[Sequence[int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a named mesh over the spatial axes.

    Like the reference's decomposition announcement
    ('Automatic MPI decomposition', fortran/mpi+cuda/heat.F90:90), callers
    should log ``mesh.shape`` once per job.
    """
    devices = list(devices if devices is not None else jax.devices())
    shape = tuple(mesh_shape) if mesh_shape else auto_mesh_shape(len(devices), ndim)
    if len(shape) != ndim:
        raise ValueError(f"mesh_shape {shape} must have {ndim} dims")
    n_used = int(np.prod(shape))
    if n_used > len(devices):
        raise ValueError(f"mesh {shape} needs {n_used} devices, have {len(devices)}")
    use = devices[:n_used]
    if len(use) > 1 and getattr(use[0], "platform", None) == "tpu":
        # Physical-topology-aware placement: mesh neighbors should be ICI
        # torus neighbors (and on multi-slice jobs the outer axis should
        # ride DCN) — the scaling-book layout rule. A naive reshape can
        # put mesh-adjacent shards on physically distant chips, turning
        # every halo ppermute into a multi-hop route. The reference gets
        # the same property from MPI_Cart_create's reorder flag
        # (fortran/mpi+cuda/heat.F90:97); on TPU the topology is known to
        # the runtime, so use it.
        try:  # best-effort: the experimental namespace may move/vanish
            from jax.experimental import mesh_utils

            dev_array = np.asarray(
                mesh_utils.create_device_mesh(shape, devices=use))
        except Exception:  # odd shapes/topologies: plain order still works
            dev_array = np.asarray(use).reshape(shape)
    else:
        dev_array = np.asarray(use).reshape(shape)
    return Mesh(dev_array, MESH_AXES[:ndim])


def validate_divisible(n_interior: int, mesh: Mesh) -> None:
    """The reference requires grids to divide evenly over ranks
    (``nx = n/nblocks(1)`` with integer division, fortran/mpi+cuda/heat.F90:92);
    we keep the constraint but fail loudly (SURVEY.md §5)."""
    for ax, sz in zip(mesh.axis_names, mesh.devices.shape):
        if n_interior % sz != 0:
            raise ValueError(
                f"grid dim {n_interior} does not divide evenly over mesh axis "
                f"{ax!r} of size {sz} (reference constraint, kept & validated)"
            )
