"""Halo (ghost) exchange over the device mesh.

TPU-native replacement for the reference's swap machinery
(``fortran/mpi+cuda/heat.F90:143-195`` and the HIP pack/unpack kernels
``fortran/hip/heat_kernel.cpp:63-150``):

- pack kernels      -> array slices of the shard (XLA fuses the "pack")
- ``mpi_sendrecv``  -> paired ``lax.ppermute`` shifts over ICI/DCN
- ``mpi_proc_null`` -> ppermute's missing-edge zeros, overwritten with the
  Dirichlet ``bc_value`` at global domain edges (non-periodic, matching
  ``pbc=.false.``, fortran/mpi+cuda/heat.F90:76 and the unpack guards
  :174-191)
- CUDA-aware vs NO_AWARE staged duality (:162-172) -> ``staged=True`` routes
  every halo slab through a host round-trip (``jax.pure_callback``), the
  honest analog of the D2H / sendrecv-on-host / H2D path; the default sends
  device buffers directly over the interconnect.

All functions run *inside* ``shard_map``: they see the local shard and use
collective axis names.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _stage_through_host(x: jax.Array) -> jax.Array:
    """Round-trip a slab through host memory (the NO_AWARE staged path,
    fortran/mpi+cuda/heat.F90:162-168: T1s = Td1s ... Td1r = T1r)."""
    return jax.pure_callback(
        lambda a: np.asarray(a), jax.ShapeDtypeStruct(x.shape, x.dtype), x,
        vmap_method="sequential",
    )


def _shift_from_prev(slab, axis_name: str, size: int):
    """Each shard receives the slab of its left/previous neighbor."""
    return lax.ppermute(slab, axis_name, [(i, i + 1) for i in range(size - 1)])


def _shift_from_next(slab, axis_name: str, size: int):
    return lax.ppermute(slab, axis_name, [(i + 1, i) for i in range(size - 1)])


def halo_exchange(
    padded: jax.Array,
    axis_names: Sequence[str],
    axis_sizes: Sequence[int],
    bc_value,
    staged: bool = False,
) -> jax.Array:
    """Refresh the one-cell ghost ring of a padded local shard.

    ``padded`` has shape ``(nx+2, ny+2[, nz+2])``: owned cells in the
    interior, ghosts in the outer ring (the reference's
    ``(1-ng:nx+ng, 1-ng:ny+ng)`` allocation, fortran/mpi+cuda/heat.F90:107).
    For each decomposed axis the owned edge slabs travel to the neighbors'
    ghost slots; at global domain edges ghosts hold ``bc_value`` (Dirichlet,
    :243-251). Corner ghosts keep ``bc_value`` — the 5/7-point stencil never
    reads them.
    """
    nd = padded.ndim
    bc = jnp.asarray(bc_value, padded.dtype)
    out = padded
    for d, (name, size) in enumerate(zip(axis_names, axis_sizes)):
        idx = lax.axis_index(name)

        def owned_slab(pos):
            sl = [slice(1, -1)] * nd
            sl[d] = slice(pos, pos + 1)
            return out[tuple(sl)]

        send_lo = owned_slab(1)        # my first owned plane  -> prev's high ghost
        send_hi = owned_slab(-2)       # my last owned plane   -> next's low ghost
        if staged:
            send_lo = _stage_through_host(send_lo)
            send_hi = _stage_through_host(send_hi)
        from_prev = _shift_from_prev(send_hi, name, size)
        from_next = _shift_from_next(send_lo, name, size)
        if staged:
            from_prev = _stage_through_host(from_prev)
            from_next = _stage_through_host(from_next)
        # Global-edge shards got zeros (no ppermute pair, == mpi_proc_null):
        # pin their ghosts to the boundary temperature instead.
        from_prev = jnp.where(idx == 0, bc, from_prev)
        from_next = jnp.where(idx == size - 1, bc, from_next)

        lo_ghost = [slice(1, -1)] * nd
        hi_ghost = [slice(1, -1)] * nd
        lo_ghost[d] = slice(0, 1)
        hi_ghost[d] = slice(-1, None)
        out = out.at[tuple(lo_ghost)].set(from_prev)
        out = out.at[tuple(hi_ghost)].set(from_next)
    return out


def halo_pad(local: jax.Array, bc_value) -> jax.Array:
    """Allocate the ghost ring around an owned shard (ghosts = bc_value)."""
    return jnp.pad(local, 1, mode="constant",
                   constant_values=jnp.asarray(bc_value, local.dtype))


def global_cell_index(
    local_shape: Tuple[int, ...],
    axis_names: Sequence[str],
) -> Tuple[jax.Array, ...]:
    """Global (row, col, ...) index arrays for the owned cells of a shard —
    the analog of locating a rank by its cartesian coords
    (fortran/mpi+cuda/heat.F90:134-137)."""
    idxs = []
    for d, name in enumerate(axis_names):
        coord = lax.axis_index(name)
        base = coord * local_shape[d]
        iota = lax.broadcasted_iota(jnp.int32, local_shape, d)
        idxs.append(base + iota)
    return tuple(idxs)
