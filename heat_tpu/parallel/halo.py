"""Halo (ghost) exchange over the device mesh.

TPU-native replacement for the reference's swap machinery
(``fortran/mpi+cuda/heat.F90:143-195`` and the HIP pack/unpack kernels
``fortran/hip/heat_kernel.cpp:63-150``):

- pack kernels      -> array slices of the shard (XLA fuses the "pack")
- ``mpi_sendrecv``  -> paired ``lax.ppermute`` shifts over ICI/DCN
- ``mpi_proc_null`` -> ppermute's missing-edge zeros, overwritten with the
  Dirichlet ``bc_value`` at global domain edges (non-periodic, matching
  ``pbc=.false.``, fortran/mpi+cuda/heat.F90:76 and the unpack guards
  :174-191). ``periodic=True`` enables the topology the reference's
  communicator is built to carry but never switches on (the ``pbc``
  periods argument of ``mpi_cart_create``, :97): the ppermute ring closes
  (last shard exchanges with the first) and no ghost is pinned.
- CUDA-aware vs NO_AWARE staged duality (:162-172) -> ``staged=True`` routes
  every halo slab through a host round-trip (``jax.pure_callback``), the
  honest analog of the D2H / sendrecv-on-host / H2D path; the default sends
  device buffers directly over the interconnect.

All functions run *inside* ``shard_map``: they see the local shard and use
collective axis names.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _stage_through_host(x: jax.Array) -> jax.Array:
    """Round-trip a slab through host memory (the NO_AWARE staged path,
    fortran/mpi+cuda/heat.F90:162-168: T1s = Td1s ... Td1r = T1r)."""
    return jax.pure_callback(
        lambda a: np.asarray(a), jax.ShapeDtypeStruct(x.shape, x.dtype), x,
        vmap_method="sequential",
    )


def _shift_from_prev(slab, axis_name: str, size: int, periodic: bool = False):
    """Each shard receives the slab of its left/previous neighbor."""
    pairs = [(i, (i + 1) % size) for i in range(size)] if periodic else [
        (i, i + 1) for i in range(size - 1)]
    return lax.ppermute(slab, axis_name, pairs)


def _shift_from_next(slab, axis_name: str, size: int, periodic: bool = False):
    pairs = [((i + 1) % size, i) for i in range(size)] if periodic else [
        (i + 1, i) for i in range(size - 1)]
    return lax.ppermute(slab, axis_name, pairs)


def halo_exchange(
    padded: jax.Array,
    axis_names: Sequence[str],
    axis_sizes: Sequence[int],
    bc_value,
    staged: bool = False,
    width: int = 1,
    periodic: bool = False,
) -> jax.Array:
    """Refresh a ``width``-cell ghost ring of a padded local shard.

    ``padded`` has shape ``(nx+2w, ny+2w[, nz+2w])``: owned cells in the
    interior, ghosts in the outer ring (the reference's
    ``(1-ng:nx+ng, 1-ng:ny+ng)`` allocation with ng=1,
    fortran/mpi+cuda/heat.F90:41,107; here ng is a parameter to support
    communication-avoiding fused steps). For each decomposed axis the owned
    edge slabs travel to the neighbors' ghost slots; at global domain edges
    ghosts hold ``bc_value`` (Dirichlet, :243-251).

    Axes are exchanged **sequentially with full-extent slabs**: the slab for
    axis d spans the entire padded extent of every other axis, so later-axis
    exchanges forward the ghosts just received — after all axes, corner
    ghost regions hold true diagonal-neighbor data (needed by fused
    multi-step updates; a single 5/7-point step never reads corners, so
    ng=1 behavior is unchanged).
    """
    nd = padded.ndim
    w = width
    bc = jnp.asarray(bc_value, padded.dtype)
    out = padded
    for d, (name, size) in enumerate(zip(axis_names, axis_sizes)):
        idx = lax.axis_index(name)

        def slab(sl_d):
            sl = [slice(None)] * nd
            sl[d] = sl_d
            return tuple(sl)

        send_lo = out[slab(slice(w, 2 * w))]       # first owned planes -> prev
        send_hi = out[slab(slice(-2 * w, -w))]     # last owned planes  -> next
        if staged:
            send_lo = _stage_through_host(send_lo)
            send_hi = _stage_through_host(send_hi)
        from_prev = _shift_from_prev(send_hi, name, size, periodic)
        from_next = _shift_from_next(send_lo, name, size, periodic)
        if staged:
            from_prev = _stage_through_host(from_prev)
            from_next = _stage_through_host(from_next)
        if not periodic:
            # Global-edge shards got zeros (no ppermute pair, ==
            # mpi_proc_null): pin their ghosts to the boundary temperature.
            from_prev = jnp.where(idx == 0, bc, from_prev)
            from_next = jnp.where(idx == size - 1, bc, from_next)

        out = out.at[slab(slice(0, w))].set(from_prev)
        out = out.at[slab(slice(-w, None))].set(from_next)
    return out


def halo_recvs(
    padded: jax.Array,
    axis_names: Sequence[str],
    axis_sizes: Sequence[int],
    bc_value,
    staged: bool = False,
    width: int = 1,
    periodic: bool = False,
) -> dict:
    """The receive half of the indep exchange: ``{d: (from_prev,
    from_next)}`` ghost slabs, each spanning the FULL padded extent of the
    other axes with earlier-axis corner data stitched in.

    Exposed separately from the writes so the overlap exchange can hand
    each rim kernel ONLY the slab it reads — a rim band that slices the
    fully-written array depends on every collective and cannot enter any
    flight window (the round-4 schedule census measured exactly that:
    1 kernel in flight out of 7, benchmarks/topology_schedule_*.json).

    Dependency chain to note: axis d's SEND slabs stitch axes e<d's fresh
    ghosts into their margins (corner forwarding), so d's ppermutes start
    only after e<d's land — the wire windows are sequential by axis; the
    per-face consumers this function enables are what lets kernels fill
    the later windows."""
    nd = padded.ndim
    w = width
    bc = jnp.asarray(bc_value, padded.dtype)

    def slab(d, sl_d):
        sl = [slice(None)] * nd
        sl[d] = sl_d
        return tuple(sl)

    recvs = {}  # d -> (from_prev, from_next)
    for d, (name, size) in enumerate(zip(axis_names, axis_sizes)):
        idx = lax.axis_index(name)
        send_lo = padded[slab(d, slice(w, 2 * w))]
        send_hi = padded[slab(d, slice(-2 * w, -w))]
        # corner forwarding: overwrite the earlier-axis margins of the
        # send slab with those axes' fresh ghosts (what the sequential
        # scheme reads from the updated array)
        for e in range(d):
            ep, en = recvs[e]
            send_lo = send_lo.at[slab(e, slice(0, w))].set(
                ep[slab(d, slice(w, 2 * w))])
            send_lo = send_lo.at[slab(e, slice(-w, None))].set(
                en[slab(d, slice(w, 2 * w))])
            send_hi = send_hi.at[slab(e, slice(0, w))].set(
                ep[slab(d, slice(-2 * w, -w))])
            send_hi = send_hi.at[slab(e, slice(-w, None))].set(
                en[slab(d, slice(-2 * w, -w))])
        if staged:
            send_lo = _stage_through_host(send_lo)
            send_hi = _stage_through_host(send_hi)
        from_prev = _shift_from_prev(send_hi, name, size, periodic)
        from_next = _shift_from_next(send_lo, name, size, periodic)
        if staged:
            from_prev = _stage_through_host(from_prev)
            from_next = _stage_through_host(from_next)
        if not periodic:
            from_prev = jnp.where(idx == 0, bc, from_prev)
            from_next = jnp.where(idx == size - 1, bc, from_next)
        recvs[d] = (from_prev, from_next)
    return recvs


def apply_recvs(padded: jax.Array, recvs: dict, width: int = 1) -> jax.Array:
    """Write the received slabs into the ghost margins (the write half of
    the indep exchange). Write order is increasing axis — later axes own
    the corners — and every consumer assembling band inputs from ``recvs``
    directly must reproduce that order (``_overlap_region_input``)."""
    w = width
    nd = padded.ndim
    out = padded
    for d in sorted(recvs):
        from_prev, from_next = recvs[d]
        sl = [slice(None)] * nd
        sl[d] = slice(0, w)
        out = out.at[tuple(sl)].set(from_prev)
        sl[d] = slice(-w, None)
        out = out.at[tuple(sl)].set(from_next)
    return out


def halo_exchange_indep(
    padded: jax.Array,
    axis_names: Sequence[str],
    axis_sizes: Sequence[int],
    bc_value,
    staged: bool = False,
    width: int = 1,
    periodic: bool = False,
) -> jax.Array:
    """``halo_exchange`` with all ghost writes made independent.

    The sequential formulation reads axis d's send slabs from the
    already-ghost-updated array (that is how corner ghosts forward), so
    each axis's update-slice depends on the previous axis's — XLA can be
    forced to materialize the intermediate (the round-3 exchange lab
    measured a full-padded-array copy per exchange in the compiled
    advance). Here every send slab is built from the ORIGINAL padded
    array, with earlier-axis corner data stitched in from those axes'
    received slabs (slab-sized updates, not full-array); the final 2*nd
    ghost writes then all read from ``padded`` only, so XLA is free to
    apply them as one in-place pass. Owned values and ghost values are
    bit-identical to ``halo_exchange`` — pinned by
    tests/test_sharded.py::test_halo_exchange_indep_bitwise.
    """
    recvs = halo_recvs(padded, axis_names, axis_sizes, bc_value,
                       staged=staged, width=width, periodic=periodic)
    return apply_recvs(padded, recvs, width=width)


def halo_pad(local: jax.Array, bc_value, width: int = 1) -> jax.Array:
    """Allocate the ghost ring around an owned shard (ghosts = bc_value)."""
    return jnp.pad(local, width, mode="constant",
                   constant_values=jnp.asarray(bc_value, local.dtype))


