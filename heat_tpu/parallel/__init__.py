from .halo import halo_exchange, halo_pad  # noqa: F401
from .mesh import MESH_AXES, auto_mesh_shape, build_mesh  # noqa: F401
