from .mesh import auto_mesh_shape, build_mesh, MESH_AXES  # noqa: F401
from .halo import halo_exchange, halo_pad  # noqa: F401
