"""Black-box known-answer canary prober (ISSUE 15's other half).

The numerics observatory (runtime/numerics.py) is white-box: it watches
real traffic from inside the scheduler. This module is the SRE-style
black-box complement (Beyer et al., *Site Reliability Engineering* ch. 6
— see PAPERS.md): a background thread that periodically submits a tiny
synthetic solve through the REAL front door — HTTP ``POST /v1/solve`` on
the gateway, the same parse/admission/lane/writer path every client
takes — and verifies the returned field against a closed-form answer.

The canary is the ``sine`` IC preset (grid.py): the product of per-axis
``sin(pi * i/(n-1))`` samples is the fundamental discrete eigenmode of
the FTCS operator under frozen-edge BCs, so every step multiplies the
whole field by the analytic factor ``lambda = 1 -
4*ndim*r*sin^2(pi/(2*(n-1)))`` and step ``s`` equals ``lambda**s * T0``
exactly (in exact arithmetic — the tolerance below covers float
rounding over ``ntime`` steps with a wide margin). A wrong-physics
regression anywhere in the stack — stencil, chunking, lane packing,
Pallas kernel, crop/publish — lands as a probe failure with a concrete
max-norm error, not as silent corruption of tenant results.

Probes run under the reserved ``_probe`` tenant so their lane-seconds
are attributable (and excludable) in the usage ledger, and at class
``batch`` so a probe can never preempt interactive traffic. Each probe
emits a structured ``probe_result`` record carrying the verdict, the
error norm, and the request's trace id; ``--probe-fail-after``
consecutive misses emit one ``probe_failed`` record (the page-worthy
signal) and the counter resets only on the next pass. ``/metrics``
exports pass/fail totals, the consecutive-failure gauge, and the last
error norm/latency; ``/statusz`` has a one-line summary.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import List, Optional

import numpy as np

from ..config import config_from_request
from ..grid import initial_condition, sine_decay_factor
from ..runtime import debug
from ..runtime.logging import json_record, master_print

# Reserved tenant for canary traffic: the usage ledger and queue-depth
# gauges key on it, so probe cost is always attributable and excludable.
PROBE_TENANT = "_probe"

# Max-norm verification tolerance per dtype: well above ntime steps of
# accumulated storage rounding on an O(1) field (f32 eps ~1e-7 * a few
# hundred steps), far below any real corruption — a single bit-flip in
# an exponent or a wrong-stencil regression misses by orders of
# magnitude.
PROBE_TOL = {"float64": 1e-9, "float32": 1e-3, "bfloat16": 5e-2}

# The canary request: tiny (one lane of the smallest default bucket for
# a handful of chunks), batch class (never preempts interactive
# traffic), frozen-edge BCs (the eigenmode argument needs them).
DEFAULT_PROBE_REQUEST = {
    "n": 64, "ndim": 2, "ntime": 200, "dtype": "float32",
    "ic": "sine", "bc": "edges",
}


class Prober:
    """Background canary thread against one gateway base URL.

    ``Prober(f"http://{gw.address}", interval_s=30).start()`` — or call
    :meth:`run_once` directly (tests, one-shot checks). The thread is a
    daemon named ``heat-tpu-prober`` and stops via :meth:`stop`.
    """

    def __init__(self, base_url: str, interval_s: float,
                 request: Optional[dict] = None, fail_after: int = 3,
                 timeout_s: float = 120.0):
        self.base_url = base_url.rstrip("/")
        self.interval_s = float(interval_s)
        self.request = dict(DEFAULT_PROBE_REQUEST, **(request or {}))
        self.fail_after = int(fail_after)
        self.timeout_s = float(timeout_s)
        self._lock = debug.make_lock("observatory:prober")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self.passes = 0
        self.fails = 0
        self.consecutive_failures = 0
        self.last_error_norm: Optional[float] = None
        self.last_latency_s: Optional[float] = None
        self.last_error: Optional[str] = None

    # --- lifecycle --------------------------------------------------------
    def start(self) -> "Prober":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="heat-tpu-prober")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)

    def _loop(self) -> None:
        # first probe after one full interval: the engine is still
        # compiling its first real traffic at startup, and a probe racing
        # that compile would report its cost as probe latency
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception as e:  # noqa: BLE001 — the prober must
                # outlive any single probe's failure; the miss IS the data
                self._record(ok=False, error_norm=None, latency_s=None,
                             status="probe-error", trace_id=None,
                             error=f"{type(e).__name__}: {e}")

    # --- one probe --------------------------------------------------------
    def run_once(self) -> dict:
        """Submit one canary request and verify it; returns the verdict
        dict (also emitted as a ``probe_result`` record)."""
        with self._lock:
            self._seq += 1
            rid = f"_probe-{self._seq:04d}"
        payload = dict(self.request, id=rid, tenant=PROBE_TENANT,
                       **{"class": "batch"})
        cfg = config_from_request(payload)
        t0 = time.perf_counter()
        rec = self._submit(payload)
        status = rec.get("status")
        trace_id = rec.get("trace_id")
        if status != "ok":
            return self._record(
                ok=False, error_norm=None,
                latency_s=time.perf_counter() - t0, status=status,
                trace_id=trace_id,
                error=str(rec.get("error") or f"status {status}"))
        T = self._fetch_field(rid)
        latency = time.perf_counter() - t0
        if T is None:
            return self._record(ok=False, error_norm=None,
                                latency_s=latency, status=status,
                                trace_id=trace_id,
                                error="record has no field payload")
        # the closed-form answer, in f64: lambda**s * T0 (grid.py)
        lam = sine_decay_factor(cfg)
        expected = (lam ** cfg.ntime
                    * initial_condition(cfg).astype(np.float64))
        err = float(np.max(np.abs(np.asarray(T, dtype=np.float64)
                                  - expected)))
        tol = PROBE_TOL.get(cfg.dtype, PROBE_TOL["float32"])
        return self._record(
            ok=err <= tol, error_norm=err, latency_s=latency,
            status=status, trace_id=trace_id,
            error=(None if err <= tol
                   else f"error norm {err:.3e} exceeds tol {tol:g}"))

    def _submit(self, payload: dict) -> dict:
        """POST the probe line and return its terminal record (the
        streaming NDJSON response's line for our id)."""
        req = urllib.request.Request(
            f"{self.base_url}/v1/solve",
            data=(json.dumps(payload) + "\n").encode(),
            headers={"Content-Type": "application/x-ndjson"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            for line in resp.read().decode().splitlines():
                if not line.strip():
                    continue
                rec = json.loads(line)
                if rec.get("id") == payload["id"]:
                    return rec
        return {"status": "missing",
                "error": "no record for the probe id in the stream"}

    def _fetch_field(self, rid: str):
        url = f"{self.base_url}/v1/requests/{rid}?field=1"
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            rec = json.loads(resp.read().decode())
        T = rec.get("T")
        return None if T is None else np.asarray(T, dtype=np.float64)

    # --- accounting -------------------------------------------------------
    def _record(self, ok: bool, error_norm, latency_s, status, trace_id,
                error=None) -> dict:
        with self._lock:
            if ok:
                self.passes += 1
                self.consecutive_failures = 0
            else:
                self.fails += 1
                self.consecutive_failures += 1
            self.last_error_norm = error_norm
            self.last_latency_s = latency_s
            self.last_error = error
            consecutive = self.consecutive_failures
        json_record("probe_result", ok=ok, error_norm=error_norm,
                    latency_s=latency_s, status=status,
                    trace_id=trace_id, error=error,
                    consecutive_failures=consecutive)
        if not ok and consecutive == self.fail_after:
            # the page-worthy signal, emitted ONCE per failure run: the
            # gateway answers but what it serves is wrong (or probes
            # cannot get through at all)
            master_print(f"prober: {consecutive} consecutive probe "
                         f"failures — last: {error}")
            json_record("probe_failed", consecutive=consecutive,
                        threshold=self.fail_after, last_error=error,
                        last_error_norm=error_norm)
        return {"ok": ok, "error_norm": error_norm, "latency_s": latency_s,
                "status": status, "trace_id": trace_id, "error": error}

    def stats(self) -> dict:
        """Point-in-time counters for /metrics and /statusz."""
        with self._lock:
            return {"interval_s": self.interval_s,
                    "passes": self.passes, "fails": self.fails,
                    "consecutive_failures": self.consecutive_failures,
                    "last_error_norm": self.last_error_norm,
                    "last_latency_s": self.last_latency_s,
                    "last_error": self.last_error}


def expected_probe_field(request: dict) -> "np.ndarray":
    """The analytic answer a probe request must return (f64): exposed so
    tests and the overhead lab certify verification without a prober."""
    cfg = config_from_request(request)
    lam = sine_decay_factor(cfg)
    return lam ** cfg.ntime * initial_condition(cfg).astype(np.float64)


def probe_urls(base_url: str) -> List[str]:
    """The endpoints one probe touches, for documentation/tests."""
    base = base_url.rstrip("/")
    return [f"{base}/v1/solve", f"{base}/v1/requests/<id>?field=1"]
