"""Content-addressed solve cache: result memoization + prefix snapshots.

At scale traffic repeats — identical solves, and parameter sweeps that
share a trajectory prefix — and the engine re-steps each one from the
initial condition, paying full device time for bytes it has already
produced. This module is the store behind ``--cache on`` (ISSUE 19):

- **Level 1 (full hit).** Every finished result is published here under
  the canonical *physics* fingerprint (``runtime.checkpoint.
  config_fingerprint`` — ``n/sigma/nu/dom_len/ndim/ic/bc/bc_value/
  dtype``; scheduler keys like id/tenant/class/deadline_ms never split
  entries) plus the step count the field actually carries. A later
  request whose fingerprint matches at exactly its ``ntime``
  short-circuits at ``Engine.submit``: the stored npz replays
  byte-identically, no lane is occupied, zero chunk programs dispatch.
- **Level 2 (prefix hit).** An entry at a *smaller* step count — a
  steady early exit's actual frontier, or a chunk-boundary lane
  snapshot the engine-checkpoint writer ingests — seeds the lane via
  the existing resume path and the engine steps only the delta.

Determinism is the whole sell: the engine's stepping is bit-exact, so a
cache hit is **byte-identical** to a recompute — a guarantee a
floating-point-accumulating serving stack (vLLM's prefix cache, say)
cannot make, and one the chaos faults (``cache-corrupt``/
``cache-stale``) and the byte-compare triage in TROUBLESHOOTING.md keep
honest.

Entry layout (one pair per ``(fingerprint, step)``)::

    <cache-dir>/<fp16hex>-<step:08d>.npz    # exact _write_result format:
                                            # T, step, n, ndim, dtype
    <cache-dir>/<fp16hex>-<step:08d>.json   # sidecar: fingerprint, step,
                                            # kind, nbytes, sha256(npz)

The npz is the same ``np.savez_compressed`` payload ``serve --out-dir``
publishes (numpy stamps fixed zip dates, so equal arrays mean equal
bytes) — a full hit with an out dir is a literal byte copy. Publishes
are atomic (temp name outside the discovery glob, then rename; sidecar
lands first so a published npz is never meta-less); identical
``(fingerprint, step)`` publishes are first-write-wins, which is safe
because the bytes are identical by construction.

Every consult re-verifies the entry like a checkpoint discovery would:
sha256 against the sidecar (bitrot), sidecar fingerprint against the
request's (a stale or mis-filed entry), then a real ``np.load`` with a
finiteness check. Any failure quarantines BOTH files to ``*.corrupt``
(out of the glob, kept for autopsy), emits a structured
``cache_quarantined`` record, and the consult falls through to the
next-best entry or a recompute — a damaged entry is never served.

Eviction is LRU by file mtime under ``--cache-max-bytes`` (a hit
touches its entry; 0 = unbounded). All counters live under one
``cache``-rank lock (``runtime/debug.LOCK_RANKS``: engine -> writer ->
cache -> observatory), so the writer thread may publish while a gateway
handler consults. The fleet router opens the same directory read-only
(shared storage, the PR-17 manifest precedent) and serves fleet-wide
full hits at the edge.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import HeatConfig
from ..runtime import debug
from ..runtime.checkpoint import config_fingerprint
from ..runtime.logging import json_record, master_print

__all__ = ["SolveCache", "config_fingerprint", "entry_name"]


def entry_name(fingerprint: str, step: int) -> str:
    """Canonical npz name for one ``(fingerprint, step)`` entry."""
    return f"{fingerprint}-{int(step):08d}.npz"


def _meta_path(npz: Path) -> Path:
    return npz.with_suffix(".json")


def _sha256_file(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _parse_entry(path: Path) -> Optional[Tuple[str, int]]:
    """``<fp>-<step:08d>.npz`` -> (fp, step), else None (foreign file)."""
    stem = path.name[:-len(".npz")]
    fp, dash, step_s = stem.rpartition("-")
    if not dash or not fp or not step_s.isdigit():
        return None
    return fp, int(step_s)


def write_entry_bytes(tmp: Path, T, cfg: HeatConfig, step: int) -> None:
    """Serialize one entry EXACTLY like scheduler._write_result does —
    the byte-identity contract hangs on the two call sites staying
    field-for-field identical."""
    with open(tmp, "wb") as f:
        np.savez_compressed(f, T=np.asarray(T), step=int(step),
                            n=cfg.n, ndim=cfg.ndim, dtype=cfg.dtype)


class SolveCache:
    """One cache directory + its counters, under one ``cache``-rank lock.

    ``plan`` is the engine's fault plan (``runtime/faults.py``): the
    ``cache-corrupt``/``cache-stale`` chaos kinds damage the consulted
    entry right before validation, which must quarantine it.
    ``readonly=True`` (the fleet router) never publishes or evicts.
    """

    def __init__(self, cache_dir, max_bytes: int = 0, plan=None,
                 readonly: bool = False):
        self.dir = Path(cache_dir)
        if not readonly:
            self.dir.mkdir(parents=True, exist_ok=True)
        self.max_bytes = int(max_bytes or 0)
        self.readonly = readonly
        self._plan = plan
        self._lock = debug.make_lock("cache:solve")
        self._consults = 0
        self.hits_full = 0
        self.hits_prefix = 0
        self.misses = 0
        self.puts = 0
        self.evictions = 0
        self.quarantined = 0
        debug.instrument_races(self, label="SolveCache",
                               exempt=frozenset({"dir", "_plan"}))

    # --- consult ----------------------------------------------------------
    def lookup(self, cfg: HeatConfig) -> Optional[dict]:
        """Best valid entry for ``cfg``: ``{"kind": "full"|"prefix",
        "fingerprint", "step", "path", "nbytes"}`` or None (miss).
        Full = an entry at exactly ``cfg.ntime``; prefix = the deepest
        entry strictly below it. Invalid candidates are quarantined and
        the next-best one is tried — a damaged entry is never served."""
        fp = config_fingerprint(cfg)
        want = int(cfg.ntime)
        with self._lock:
            self._consults += 1
            consult = self._consults
        if self._plan is not None:
            self._plan.damage_cache(self.dir, fp, consult)
        # best-first: the exact step, then prefixes by descending depth
        steps = sorted((s for cfp, s in self._entries()
                        if cfp == fp and s <= want), reverse=True)
        for step in steps:
            path = self.dir / entry_name(fp, step)
            reason = self._validate(path, fp, step)
            if reason is not None:
                self._quarantine(path, fp, step, reason)
                continue
            try:
                os.utime(path)            # LRU touch (best effort)
            except OSError:
                pass
            nbytes = path.stat().st_size
            kind = "full" if step == want else "prefix"
            with self._lock:
                if kind == "full":
                    self.hits_full += 1
                else:
                    self.hits_prefix += 1
            return {"kind": kind, "fingerprint": fp, "step": step,
                    "path": str(path), "nbytes": int(nbytes)}
        with self._lock:
            self.misses += 1
        return None

    def _entries(self) -> List[Tuple[str, int]]:
        if not self.dir.is_dir():
            return []
        out = []
        for p in self.dir.glob("*.npz"):
            parsed = _parse_entry(p)
            if parsed is not None:
                out.append(parsed)
        return out

    def _validate(self, path: Path, fp: str, step: int) -> Optional[str]:
        """None when the entry is servable, else the quarantine reason.
        Order matters: the sidecar fingerprint check catches a stale or
        mis-filed entry (``cache-stale``) before the content hash catches
        bitrot (``cache-corrupt``); a final real load catches everything
        a hash cannot (we hash what we wrote, not what np.load needs)."""
        meta_p = _meta_path(path)
        try:
            meta = json.loads(meta_p.read_text())
        except Exception as e:  # noqa: BLE001 — every decode failure is
            return f"sidecar unreadable ({type(e).__name__}: {e})"
        if meta.get("fingerprint") != fp:
            return (f"stale: sidecar fingerprint "
                    f"{meta.get('fingerprint')!r} != request {fp!r}")
        if int(meta.get("step", -1)) != step:
            return f"stale: sidecar step {meta.get('step')} != {step}"
        try:
            if _sha256_file(path) != meta.get("sha256"):
                return "content hash mismatch (bitrot or torn write)"
            with np.load(path, allow_pickle=False) as z:
                if int(z["step"]) != step:
                    return f"payload step {int(z['step'])} != {step}"
                T = np.asarray(z["T"])
                if T.dtype.name == "bfloat16":
                    T = T.astype(np.float32)
                if not np.isfinite(T).all():
                    return "non-finite field"
        except Exception as e:  # noqa: BLE001
            return f"unreadable ({type(e).__name__}: {e})"
        return None

    def _quarantine(self, path: Path, fp: str, step: int,
                    reason: str) -> None:
        """Rename entry + sidecar to ``*.corrupt`` (out of every glob,
        kept for autopsy) and emit the structured record operators
        alert on. A read-only (router) cache cannot rename on shared
        storage it does not own — it just refuses to serve the entry."""
        quarantined = []
        if not self.readonly:
            for p in (path, _meta_path(path)):
                try:
                    q = p.with_name(p.name + ".corrupt")
                    p.rename(q)
                    quarantined.append(str(q))
                except OSError:
                    pass
        with self._lock:
            self.quarantined += 1
        master_print(f"solve cache: quarantined {path.name} ({reason}) "
                     f"— recomputing")
        json_record("cache_quarantined", fingerprint=fp, step=int(step),
                    path=str(path), reason=reason,
                    quarantined=quarantined)

    @staticmethod
    def load(path) -> Tuple[np.ndarray, int]:
        """One validated entry's field + step (the prefix-seed read)."""
        with np.load(path, allow_pickle=False) as z:
            return np.asarray(z["T"]), int(z["step"])

    def replay(self, entry_path, out_dir, req_id: str) -> Path:
        """Full-hit publish: byte-copy the cached npz to the out dir
        under the hitting request's id (atomic temp+rename — the same
        torn-file discipline as ``_write_result``, and byte-identical to
        the cold-miss artifact because it IS those bytes)."""
        d = Path(out_dir)
        d.mkdir(parents=True, exist_ok=True)
        path = d / f"{req_id}.npz"
        tmp = d / (path.name + ".tmp")
        shutil.copyfile(entry_path, tmp)
        tmp.rename(path)
        return path

    # --- publish ----------------------------------------------------------
    def put(self, cfg: HeatConfig, step: int, T=None, src_path=None,
            kind: str = "result") -> Optional[Path]:
        """Publish one entry under ``(fingerprint(cfg), step)`` — from
        the published result file (``src_path``, a byte copy) or a host
        field (``T``, serialized identically). First-write-wins: an
        existing entry's bytes are identical by construction. Best
        effort by design — a full disk must fail the cache, never the
        request (runs on the writer thread's publish path)."""
        if self.readonly:
            return None
        try:
            fp = config_fingerprint(cfg)
            path = self.dir / entry_name(fp, step)
            if path.exists():
                return path
            self.dir.mkdir(parents=True, exist_ok=True)
            tmp = self.dir / (path.name + ".tmp")
            if src_path is not None:
                shutil.copyfile(src_path, tmp)
            else:
                write_entry_bytes(tmp, T, cfg, step)
            meta = {"fingerprint": fp, "step": int(step), "kind": kind,
                    "nbytes": tmp.stat().st_size,
                    "sha256": _sha256_file(tmp)}
            meta_tmp = self.dir / (_meta_path(path).name + ".tmp")
            meta_tmp.write_text(json.dumps(meta, sort_keys=True) + "\n")
            # sidecar first: a published npz is never sidecar-less
            meta_tmp.rename(_meta_path(path))
            tmp.rename(path)
        except Exception as e:  # noqa: BLE001 — cache misses are safe;
            # a failed publish must not poison the writer retry path
            master_print(f"solve cache: publish failed for step {step} "
                         f"({type(e).__name__}: {e}) — entry skipped")
            for t in (locals().get("tmp"), locals().get("meta_tmp")):
                if t is not None:
                    try:
                        Path(t).unlink(missing_ok=True)
                    except OSError:
                        pass
            return None
        with self._lock:
            self.puts += 1
        self._evict()
        return path

    # --- eviction ---------------------------------------------------------
    def _evict(self) -> None:
        """LRU by npz mtime until total entry bytes fit
        ``max_bytes`` (0 = unbounded). Sidecars ride along."""
        if self.max_bytes <= 0 or self.readonly:
            return
        entries = []
        total = 0
        for fp, step in self._entries():
            p = self.dir / entry_name(fp, step)
            try:
                st = p.stat()
                msize = _meta_path(p).stat().st_size
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size + msize, p))
            total += st.st_size + msize
        entries.sort()                       # oldest mtime first
        evicted = 0
        for _, size, p in entries:
            if total <= self.max_bytes:
                break
            for victim in (p, _meta_path(p)):
                try:
                    victim.unlink(missing_ok=True)
                except OSError:
                    pass
            total -= size
            evicted += 1
            master_print(f"solve cache: evicted {p.name} (LRU, "
                         f"{total} B retained <= --cache-max-bytes "
                         f"{self.max_bytes})")
        if evicted:
            with self._lock:
                self.evictions += evicted

    # --- reporting --------------------------------------------------------
    def bytes_total(self) -> int:
        total = 0
        for fp, step in self._entries():
            p = self.dir / entry_name(fp, step)
            try:
                total += p.stat().st_size + _meta_path(p).stat().st_size
            except OSError:
                pass
        return total

    def stats(self) -> Dict:
        """The /metrics / /statusz / summary() food."""
        with self._lock:
            counters = {"consults": self._consults,
                        "hits_full": self.hits_full,
                        "hits_prefix": self.hits_prefix,
                        "misses": self.misses,
                        "puts": self.puts,
                        "evictions": self.evictions,
                        "quarantined": self.quarantined}
        return {"dir": str(self.dir), "max_bytes": self.max_bytes,
                "readonly": self.readonly,
                "entries": len(self._entries()),
                "bytes": self.bytes_total(), **counters}
