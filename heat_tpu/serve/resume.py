"""Crash-safe engine resume: rebuild a serving engine from its manifest.

The write half lives in ``scheduler.Engine._engine_checkpoint`` (one
on-device copy per occupied lane + a JSON manifest submitted to the FIFO
writer last, so a manifest on disk proves everything it references is
durable) and ``runtime/checkpoint.py`` (atomic files, validation,
quarantine, generation discovery).  This module is the read half:
``resume_engine`` finds the newest restorable generation and replays
every recovered request back through ``Engine.submit`` — the one
admission door — in original submit order, so the policy queues
(fifo/edf/fair) reproduce the checkpointed dispatch order without the
manifest having to serialize policy internals.

Recovery contract (tests/test_serve_resume.py):

- **In-flight** entries re-enter with a ``_restore`` payload carrying
  the checkpointed host field, remaining-step count, chunk count, usage
  partials, and numerics-observatory state; the admitting lane fill
  continues them at their last checkpointed boundary via the same
  ``load_lane`` path ``maybe_grow`` transplants ride, so the continued
  solve is bit-identical to an uninterrupted run.
- **Queued** entries re-enter with an empty payload — same config, same
  SLO fields, fresh initial condition, original relative order.
- **Done** ids are NOT replayed; they come back in the returned skip
  set so a file-driven front door does not re-submit finished work.
- Usage billing resumes from the stamped ``lane_s`` partial and the
  step count spans incarnations by construction — no double billing.
- A fingerprint mismatch between the manifest entry and its
  reconstructed config is a hard error: resuming a lane onto different
  physics must be loud, never silent.
"""

from __future__ import annotations

from typing import Dict, Set

from ..config import HeatConfig
from ..runtime import checkpoint as ckpt_mod
from ..runtime.logging import json_record, master_print


def config_from_manifest(d: dict) -> HeatConfig:
    """Rebuild a ``HeatConfig`` from its ``dataclasses.asdict`` form
    (JSON turned the ``mesh_shape`` tuple into a list)."""
    d = dict(d)
    if d.get("mesh_shape") is not None:
        d["mesh_shape"] = tuple(int(x) for x in d["mesh_shape"])
    return HeatConfig(**d)


def resume_engine(eng, resume_dir) -> Set[str]:
    """Re-admit every request recovered from the newest valid engine
    manifest in ``resume_dir`` into ``eng``. Returns the set of request
    ids the manifest accounts for (in-flight + queued + done) so callers
    can skip re-submitting them. See :func:`resume_engine_detail` for
    the structured form (the fleet router's steal path needs to know
    which ids were re-admitted vs already done)."""
    d = resume_engine_detail(eng, resume_dir)
    return set(d["recovered"]) | set(d["done"])


def resume_engine_detail(eng, resume_dir, skip_known: bool = False) -> Dict:
    """Re-admit every request recovered from the newest valid engine
    manifest in ``resume_dir`` into ``eng`` — a fresh not-yet-running
    Engine (``serve --resume``) or a LIVE one (the fleet router's
    checkpoint-handoff steal, POST /v1/resume): ``Engine.submit`` is the
    one admission door either way and it is thread-safe. Returns
    ``{"generation", "recovered", "done"}`` where ``recovered`` lists
    the in-flight + queued ids re-admitted (replay order) and ``done``
    the ids the manifest says already finished.

    No restorable generation (empty/missing dir, or every candidate
    quarantined) is a loud fresh start, not an error — the service must
    come up even when the checkpoint state is gone.

    ``skip_known=True`` (the live ``POST /v1/resume`` door) tolerates
    manifest entries whose ids this engine already knows: the router's
    retry/re-drive can race the manifest landing, and the raced rows
    must not poison the rest of the replay. The strict default stays for
    ``serve --resume`` — a fresh engine with colliding ids is a caller
    bug, not a race.
    """
    manifest, path = ckpt_mod.latest_engine_manifest(resume_dir)
    if manifest is None:
        master_print(f"engine resume: no restorable generation under "
                     f"{resume_dir} — starting fresh")
        return {"generation": 0, "recovered": [], "done": []}
    gen = int(manifest["generation"])
    with eng._lock:
        # never re-publish a generation number this lineage already used
        eng._engine_ckpt_next = max(eng._engine_ckpt_next, gen + 1)
        eng._engine_ckpt_gen = gen
    recovered = []
    skipped = 0
    rows = ([("inflight", e) for e in manifest["inflight"]]
            + [("queued", e) for e in manifest["queued"]])
    # original submit order: the policy queues' deterministic tiebreak
    # (req.seq, reassigned monotonically here) reproduces pop order
    rows.sort(key=lambda kv: int(kv[1].get("seq", 0)))
    for state, e in rows:
        cfg = config_from_manifest(e["cfg"])
        fp = ckpt_mod.config_fingerprint(cfg)
        if fp != e["fingerprint"]:
            raise ValueError(
                f"engine resume: request {e['id']!r} fingerprint mismatch "
                f"(manifest {e['fingerprint']}, rebuilt config {fp}) — "
                f"the manifest no longer matches this build's physics "
                f"fields; refusing to continue a different solve")
        restore = {}
        if state == "inflight":
            T, remaining = ckpt_mod.load_engine_field(
                resume_dir, gen, e["id"], fp)
            restore = {"T": T, "remaining": int(remaining),
                       "chunks": int(e.get("chunks", 0)),
                       "lane_s": float(e.get("lane_s", 0.0)),
                       "numerics": e.get("numerics")}
        try:
            rid = eng.submit(cfg, request_id=e["id"],
                             deadline_ms=e.get("deadline_ms"),
                             tenant=e.get("tenant"),
                             slo_class=e.get("class"),
                             until=e.get("until"), tol=e.get("tol"),
                             _restore=restore)
        except ValueError as ex:
            if skip_known and "duplicate request id" in str(ex):
                skipped += 1
                continue
            raise
        recovered.append(rid)
        json_record("serve_resumed", id=rid, generation=gen, state=state,
                    steps_done=int(e.get("steps_done", 0)),
                    remaining=int(e.get("remaining", cfg.ntime)),
                    placement=e.get("placement"))
    done = list(manifest.get("done", ()))
    master_print(f"engine resume: generation {gen} ({path.name}) — "
                 f"{len(manifest['inflight'])} in-flight re-admitted at "
                 f"their last boundary, {len(manifest['queued'])} queued "
                 f"re-queued in policy order, {len(done)} already done"
                 + (f", {skipped} already known here (skipped)"
                    if skipped else ""))
    return {"generation": gen, "recovered": recovered, "done": done,
            "skipped": skipped}
