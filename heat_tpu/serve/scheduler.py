"""Admission queue + shape bucketing + continuous batching (host half).

The serving contract, in the shape of an inference server's scheduler:

- **Admission**: ``Engine.submit(cfg)`` validates a request against the
  bucket table and enqueues it. A request the engine cannot serve (side
  larger than the biggest bucket; periodic BC, which has no padded-lane
  form) is *rejected as a record*, never as an engine error — multi-tenant
  serving must not let one bad request take down the queue.
- **Bucketing**: requests are grouped by ``BucketKey`` (ndim, smallest
  bucket side that fits, dtype, BC). One group = one stacked lane array =
  at most one stepping-program compile per (bucket, lane-count) no matter
  how many requests flow through it.
- **Continuous batching**: the chunk loop never stops for a single lane.
  At each chunk boundary the scheduler fetches the (L,) remaining-step
  vector — the only per-boundary D2H — extracts finished lanes, hands
  their fields to the async writeback pipeline (``runtime/async_io``,
  the same bounded-queue writer the checkpoint path uses), and swaps
  queued requests into the freed lanes while the other lanes keep their
  state. This is Orca-style iteration-level scheduling (PAPERS.md) with
  the FTCS chunk as the iteration.
- **Fault isolation**: an injected or real sink failure on one request's
  writeback (``sink-error`` in runtime/faults.py grammar) fails THAT
  request's record; transient errors still ride the writer's bounded
  in-thread retry, and the engine keeps draining the other lanes either
  way.

Per-request structured JSON records (queue wait, steps/s, lane id) go
through ``runtime/logging``; each request also keeps a python-level record
for library callers (``Engine.results()``).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, List, Optional

import numpy as np

from ..config import HeatConfig
from ..grid import initial_condition
from ..runtime import async_io, faults
from ..runtime.logging import json_record
from .engine import BucketKey, LaneEngine, wall_clock


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine-level knobs (the per-request physics lives in HeatConfig)."""

    lanes: int = 4            # max concurrent requests per bucket group
    chunk: int = 16           # steps per device program call (the swap
                              # granularity of continuous batching)
    buckets: tuple = (256, 512, 1024)  # grid-side buckets; a request is
                              # padded up to the smallest side that fits
    out_dir: Optional[str] = None  # writeback directory (<id>.npz); None =
                              # results kept in-memory on the records
    keep_fields: bool = False  # keep final fields on records even when
                              # writing files (tests / library callers)
    emit_records: bool = True  # print one JSON line per finished request

    def __post_init__(self):
        if self.lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {self.lanes}")
        if self.chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {self.chunk}")
        if not self.buckets or any(b < 3 for b in self.buckets):
            raise ValueError(f"buckets must be sides >= 3, got {self.buckets}")


@dataclasses.dataclass
class Request:
    """One admitted solve request."""

    id: str
    cfg: HeatConfig
    submit_t: float
    key: Optional[BucketKey] = None   # None once rejected


def _bucket_for(cfg: HeatConfig, buckets) -> Optional[int]:
    """Smallest bucket side that fits the request, or None (overflow)."""
    for b in sorted(buckets):
        if cfg.n <= b:
            return b
    return None


def _write_result(out_dir, req_id: str, T: np.ndarray, cfg: HeatConfig):
    """Atomic-publish one request's final field (same torn-file discipline
    as runtime/checkpoint.py: temp name outside any discovery glob)."""
    from pathlib import Path

    d = Path(out_dir)
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"{req_id}.npz"
    tmp = d / (path.name + ".tmp")
    with open(tmp, "wb") as f:
        np.savez_compressed(f, T=np.asarray(T), step=cfg.ntime,
                            n=cfg.n, ndim=cfg.ndim, dtype=cfg.dtype)
    tmp.rename(path)
    return path


class Engine:
    """Request-driven batched execution engine (library API).

    >>> eng = Engine(ServeConfig(lanes=4, chunk=8, buckets=(64,)))
    >>> rid = eng.submit(HeatConfig(n=32, ntime=100, dtype="float64"))
    >>> records = eng.results()   # drains the queue, returns all records

    ``submit`` only enqueues; ``run``/``results`` executes every admitted
    request to completion via continuous batching and returns the records
    in submit order.
    """

    def __init__(self, scfg: ServeConfig = ServeConfig()):
        self.scfg = scfg
        self._queues: Dict[BucketKey, collections.deque] = {}
        self._records: List[dict] = []
        self._by_id: Dict[str, dict] = {}
        self._seq = 0
        # one compiled-program cache for the engine's lifetime: repeated
        # runs (a long-lived server draining wave after wave) never pay a
        # second (bucket, lane-count) compile
        self._compiled: Dict = {}
        self.step_compiles = 0    # stepping programs built (the criterion:
                                  # at most one per (bucket, lane-count))
        self.compile_s = 0.0

    # --- admission --------------------------------------------------------
    def submit(self, cfg: HeatConfig, request_id: Optional[str] = None) -> str:
        """Admit one request; returns its id. Unservable requests become
        status='rejected' records instead of raising (see module doc)."""
        rid = request_id or f"req-{self._seq:04d}"
        self._seq += 1
        if rid in self._by_id:
            raise ValueError(f"duplicate request id {rid!r}")
        rec = {"id": rid, "n": cfg.n, "ndim": cfg.ndim, "ntime": cfg.ntime,
               "dtype": cfg.dtype, "bc": cfg.bc, "status": "queued",
               "bucket": None, "lane": None, "queue_wait_s": None,
               "solve_s": None, "steps_per_s": None, "error": None}
        self._records.append(rec)
        self._by_id[rid] = rec
        if cfg.bc == "periodic":
            self._reject(rec, "unsupported-bc: periodic has no padded-lane "
                              "form (wraparound would wrap at the bucket "
                              "edge, not the request edge)")
            return rid
        b = _bucket_for(cfg, self.scfg.buckets)
        if b is None:
            self._reject(rec, f"bucket-overflow: request side {cfg.n} "
                              f"exceeds the biggest bucket "
                              f"{max(self.scfg.buckets)}")
            return rid
        key = BucketKey(ndim=cfg.ndim, n=b, dtype=cfg.dtype, bc=cfg.bc)
        rec["bucket"] = b
        self._queues.setdefault(key, collections.deque()).append(
            Request(id=rid, cfg=cfg, submit_t=wall_clock(), key=key))
        return rid

    def _reject(self, rec: dict, reason: str) -> None:
        rec["status"] = "rejected"
        rec["error"] = reason
        self._emit(rec)

    def _emit(self, rec: dict) -> None:
        if self.scfg.emit_records:
            json_record("serve_request",
                        **{k: v for k, v in rec.items() if k != "T"})

    # --- execution --------------------------------------------------------
    def run(self) -> List[dict]:
        """Drain every queued request through continuous batching; returns
        all records (submit order). Reentrant: new submits after a run are
        served by the next run against warm compiled programs."""
        writer = async_io.SnapshotWriter()
        try:
            for key in list(self._queues):
                q = self._queues[key]
                if q:
                    self._run_group(key, q, writer)
        finally:
            # every queued writeback lands (or fails per-request) before
            # results are reported; per-request jobs swallow their own
            # failures, so a surviving writer error here is a real bug
            writer.drain()
        return list(self._records)

    def results(self) -> List[dict]:
        """``run`` + records (the common library call)."""
        if any(self._queues.values()):
            self.run()
        return list(self._records)

    def _run_group(self, key: BucketKey, q, writer) -> None:
        """Continuous-batching loop for one bucket group."""
        lanes = min(self.scfg.lanes, len(q))
        ckey = (key, lanes, self.scfg.chunk)
        fresh = ckey not in self._compiled
        eng = LaneEngine(key, lanes, self.scfg.chunk,
                         compiled_cache=self._compiled)
        if fresh:
            self.step_compiles += 1
            self.compile_s += eng.compile_s
        occupant: List[Optional[Request]] = [None] * lanes

        def fill_free_lanes():
            for lane in range(lanes):
                if occupant[lane] is None and q:
                    req = q.popleft()
                    now = wall_clock()
                    rec = self._by_id[req.id]
                    rec["lane"] = lane
                    rec["queue_wait_s"] = round(now - req.submit_t, 6)
                    rec["status"] = "running"
                    rec["_start_t"] = now
                    T0 = initial_condition(req.cfg)
                    eng.load_lane(lane, T0, float(req.cfg.r),
                                  req.cfg.ntime, req.cfg.bc_value)
                    occupant[lane] = req

        fill_free_lanes()
        while any(o is not None for o in occupant):
            rem = eng.step_chunk()
            for lane in range(lanes):
                req = occupant[lane]
                if req is not None and rem[lane] == 0:
                    self._finish(eng, lane, req, writer)
                    occupant[lane] = None
            fill_free_lanes()   # continuous batching: freed lanes refill
                                # while the others' state stays put

    def _finish(self, eng: LaneEngine, lane: int, req: Request,
                writer) -> None:
        """Extract a finished lane and hand it to the async writeback."""
        rec = self._by_id[req.id]
        now = wall_clock()
        start = rec.pop("_start_t", now)
        rec["solve_s"] = round(now - start, 6)
        rec["steps_per_s"] = (round(req.cfg.ntime / (now - start), 3)
                              if now > start else None)
        T = eng.extract_lane(lane, req.cfg.n)
        if self.scfg.keep_fields or not self.scfg.out_dir:
            rec["T"] = T
        cfg, scfg = req.cfg, self.scfg
        attempts = {"n": 0}

        def job():
            # Runs in the writer thread. Transient sink errors are
            # re-raised so the SnapshotWriter's bounded in-thread retry
            # (backoff, same budget as checkpoints) gets its shot; a final
            # failure is recorded on THIS request and swallowed — it must
            # not poison writer._exc and kill the other lanes' drain.
            attempts["n"] += 1
            try:
                plan = faults.plan_for(cfg)
                if plan is not None:
                    plan.sink_fault(cfg.ntime)
                if scfg.out_dir:
                    rec["path"] = str(_write_result(scfg.out_dir, req.id,
                                                    T, cfg))
                rec["status"] = "ok"
            except BaseException as e:  # noqa: BLE001 — per-request record
                if async_io.is_transient(e) and attempts["n"] <= writer.retries:
                    raise
                rec["status"] = "error"
                rec["error"] = f"{type(e).__name__}: {e}"
            self._emit(rec)

        writer.submit(job)

    # --- reporting --------------------------------------------------------
    def summary(self) -> dict:
        by_status = collections.Counter(r["status"] for r in self._records)
        return {"requests": len(self._records), **dict(by_status),
                "step_compiles": self.step_compiles,
                "compile_s": round(self.compile_s, 3)}
