"""Admission queue + shape bucketing + dispatch-ahead continuous batching.

The serving contract, in the shape of an inference server's scheduler:

- **Admission**: ``Engine.submit(cfg)`` validates a request against the
  bucket table and enqueues it. A request the engine cannot serve (side
  larger than the biggest bucket; periodic BC, which has no padded-lane
  form) is *rejected as a record*, never as an engine error — multi-tenant
  serving must not let one bad request take down the queue.
- **Bucketing**: requests are grouped by ``BucketKey`` (ndim, smallest
  bucket side that fits, dtype, BC). One group = one stacked lane array =
  at most one stepping-program compile per (bucket, lane-tier) no matter
  how many requests flow through it — lane counts round UP to power-of-two
  tiers (``engine.lane_tier``) so uneven waves share programs.
- **Continuous batching, dispatch-ahead**: the chunk loop never stops for
  a single lane, and (the PR-4 rework) the device never waits on the host
  between chunks. The scheduler keeps ``dispatch_depth`` chunk programs in
  flight per group and inspects the remaining-step vector of the OLDEST
  one — fetched while the newer chunks compute behind it, so the
  boundary's D2H and python bookkeeping overlap device work instead of
  fencing it. Finished lanes take a one-lane on-device snapshot
  (``runtime/async_io.lane_snapshot``) and stepping resumes immediately;
  the D2H + result write happen wholly in the ``SnapshotWriter`` thread.
  ``Engine.run`` round-robins chunk dispatch across all live bucket
  groups, so one group's boundary bookkeeping hides under another group's
  compute. ``dispatch_depth=0`` is the fully synchronous debugging
  fallback (fetch-every-boundary, extraction on the scheduler thread —
  the PR-3 shape).
- **Determinism of the boundary**: the device decrements each lane's
  remaining count by exactly one per step while positive, so the host
  mirrors the countdown and PREDICTS every chunk's post-step vector at
  dispatch time. Prediction drives dispatch policy (is another chunk
  useful; steady chunk vs tail); the fetched vector stays the ground
  truth for finishing lanes — and must equal the prediction, enforced
  per boundary (a divergence means the masking contract broke, and a
  serving engine must never silently mis-serve). Lanes whose occupant
  was swapped in after a chunk was dispatched are guarded by a per-lane
  epoch: a stale in-flight chunk cannot "finish" the new occupant.
- **Tail chunks**: when every live lane's remaining count has dropped
  below the chunk (and far enough that it saves compute), the group
  dispatches a lazily-precompiled quarter-chunk tail program instead of
  a mostly-masked full chunk — at most ONE extra compile per
  (bucket, lane-tier), waste bounded by the tail size.
- **Fault isolation**: an injected or real sink failure on one request's
  writeback (``sink-error`` in runtime/faults.py grammar) fails THAT
  request's record; transient errors still ride the writer's bounded
  in-thread retry, and the engine keeps draining the other lanes either
  way.

Per-request structured JSON records (queue wait, steps/s, lane id) go
through ``runtime/logging``; each request also keeps a python-level record
for library callers (``Engine.results()``). Records are mutated from both
the scheduler thread and the writer thread — one engine-wide lock guards
every record mutation and every ``json_record`` emission so JSON lines
cannot interleave mid-line.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Dict, List, Optional

import numpy as np

from ..config import HeatConfig
from ..grid import initial_condition
from ..runtime import async_io, faults
from ..runtime.logging import json_record
from .engine import BucketKey, LaneEngine, lane_tier, wall_clock


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine-level knobs (the per-request physics lives in HeatConfig)."""

    lanes: int = 4            # max concurrent requests per bucket group
                              # (waves round up to power-of-two tiers
                              # capped here — see engine.lane_tier)
    chunk: int = 16           # steps per device program call (the swap
                              # granularity of continuous batching)
    buckets: tuple = (256, 512, 1024)  # grid-side buckets; a request is
                              # padded up to the smallest side that fits
    dispatch_depth: int = 2   # chunk programs kept in flight per group
                              # before the scheduler blocks on a boundary
                              # fetch; 1 = fetch the chunk just dispatched
                              # (pipelined bookkeeping only), 0 = fully
                              # synchronous fallback for debugging (the
                              # PR-3 fence-every-chunk shape, extraction
                              # on the scheduler thread)
    out_dir: Optional[str] = None  # writeback directory (<id>.npz); None =
                              # results kept in-memory on the records
    keep_fields: bool = False  # keep final fields on records even when
                              # writing files (tests / library callers)
    emit_records: bool = True  # print one JSON line per finished request

    def __post_init__(self):
        if self.lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {self.lanes}")
        if self.chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {self.chunk}")
        if self.dispatch_depth < 0:
            raise ValueError(f"dispatch_depth must be >= 0 (0 = sync "
                             f"fallback), got {self.dispatch_depth}")
        if not self.buckets or any(b < 3 for b in self.buckets):
            raise ValueError(f"buckets must be sides >= 3, got {self.buckets}")


@dataclasses.dataclass
class Request:
    """One admitted solve request."""

    id: str
    cfg: HeatConfig
    submit_t: float
    key: Optional[BucketKey] = None   # None once rejected


def _bucket_for(cfg: HeatConfig, buckets) -> Optional[int]:
    """Smallest bucket side that fits the request, or None (overflow)."""
    for b in sorted(buckets):
        if cfg.n <= b:
            return b
    return None


def _write_result(out_dir, req_id: str, T: np.ndarray, cfg: HeatConfig):
    """Atomic-publish one request's final field (same torn-file discipline
    as runtime/checkpoint.py: temp name outside any discovery glob)."""
    from pathlib import Path

    d = Path(out_dir)
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"{req_id}.npz"
    tmp = d / (path.name + ".tmp")
    with open(tmp, "wb") as f:
        np.savez_compressed(f, T=np.asarray(T), step=cfg.ntime,
                            n=cfg.n, ndim=cfg.ndim, dtype=cfg.dtype)
    tmp.rename(path)
    return path


class _GroupRunner:
    """Dispatch-ahead continuous batching for ONE bucket group.

    Owns the group's ``LaneEngine``, occupancy, the host-side countdown
    mirror (``dev_rem`` — exact, because the device decrements remaining
    by one per step while positive), and the in-flight deque of
    ``(seq, remaining-handle, predicted-vector)`` chunk boundaries.
    ``Engine.run`` drives many runners round-robin; each tick dispatches
    until ``dispatch_depth`` chunks are queued, then takes at most one
    boundary (the oldest handle).
    """

    def __init__(self, outer: "Engine", key: BucketKey, q, writer):
        self.outer = outer
        self.key = key
        self.q = q
        self.writer = writer
        scfg = outer.scfg
        self.chunk = scfg.chunk
        self.depth = max(1, scfg.dispatch_depth)
        self.lanes = lane_tier(min(len(q), scfg.lanes), scfg.lanes)
        self.eng = LaneEngine(key, self.lanes, scfg.chunk,
                              compiled_cache=outer._compiled,
                              on_compile=outer._note_compile)
        self.occupant: List[Optional[Request]] = [None] * self.lanes
        # first dispatch seq whose chunk covers the lane's CURRENT
        # occupant: an in-flight chunk older than the epoch shows the
        # PREVIOUS occupant's zeros and must not finish the new one
        self.epoch = [0] * self.lanes
        self.dev_rem = np.zeros(self.lanes, dtype=np.int64)
        self.seq = 0                        # next dispatch's sequence id
        self.inflight: collections.deque = collections.deque()
        self.idle_from: Optional[float] = None  # group device queue empty
                                                # since (boundary gaps only)
        self._fill()

    # --- admission into lanes --------------------------------------------
    def _fill(self) -> None:
        """Swap queued requests into every free lane (continuous
        batching). The IC build + H2D load run on the scheduler thread,
        but with chunks in flight they overlap device compute instead of
        extending a fence."""
        for lane in range(self.lanes):
            if self.occupant[lane] is None and self.q:
                req = self.q.popleft()
                now = wall_clock()
                rec = self.outer._by_id[req.id]
                with self.outer._lock:
                    rec["lane"] = lane
                    rec["queue_wait_s"] = round(now - req.submit_t, 6)
                    rec["status"] = "running"
                    rec["_start_t"] = now
                T0 = initial_condition(req.cfg)
                self.eng.load_lane(lane, T0, float(req.cfg.r),
                                   req.cfg.ntime, req.cfg.bc_value)
                self.occupant[lane] = req
                self.epoch[lane] = self.seq
                self.dev_rem[lane] = req.cfg.ntime

    def _live_remaining(self) -> List[int]:
        return [int(self.dev_rem[i]) for i, o in enumerate(self.occupant)
                if o is not None and self.dev_rem[i] > 0]

    # --- dispatch side ----------------------------------------------------
    def dispatch_fill(self) -> None:
        """Queue chunk programs until ``dispatch_depth`` are in flight or
        no lane has steps left to run. Pure host->device enqueue: no
        fetch, no fence."""
        while len(self.inflight) < self.depth:
            live = self._live_remaining()
            if not live:
                break
            k = self.chunk
            tail = self.eng.tail
            if tail is not None and max(live) <= self.chunk - tail:
                # every live lane finishes inside the chunk, with enough
                # headroom that ceil(rem/tail) tail programs compute
                # strictly fewer masked steps than one full chunk
                k = tail
                self.outer.tail_chunks += 1
            handle = self.eng.dispatch_chunk(k)
            if self.idle_from is not None:
                self.outer.device_idle_s += wall_clock() - self.idle_from
                self.idle_from = None
            np.maximum(self.dev_rem - k, 0, out=self.dev_rem)
            self.inflight.append(
                (self.seq, handle, self.dev_rem.astype(np.int32)))
            self.seq += 1
            self.outer.chunks_dispatched += 1

    # --- boundary side ----------------------------------------------------
    def process_boundary(self) -> None:
        """Take one chunk boundary: fetch the OLDEST in-flight remaining
        vector (the newer chunks keep computing behind the transfer),
        retire lanes that finished, refill from the queue."""
        outer = self.outer
        if self.inflight:
            seq, handle, predicted = self.inflight.popleft()
            t0 = wall_clock()
            rem = self.eng.fetch_remaining(handle)
            outer.boundary_wait_s += wall_clock() - t0
            outer.boundary_waits += 1
            if not self.inflight:
                self.idle_from = wall_clock()
            if not np.array_equal(rem, predicted):
                raise RuntimeError(
                    f"serve dispatch-ahead desync for bucket {self.key}: "
                    f"device remaining {rem.tolist()} != host-predicted "
                    f"{predicted.tolist()} at chunk {seq} — the lane "
                    f"masking contract broke; results cannot be trusted")
            for lane in range(self.lanes):
                req = self.occupant[lane]
                if (req is not None and rem[lane] == 0
                        and seq >= self.epoch[lane]):
                    outer._finish_async(self.eng, lane, req, self.writer)
                    self.occupant[lane] = None
        else:
            # nothing in flight and nothing left to step: occupants whose
            # countdown is already settled at zero (ntime=0 admits, or
            # the final boundary was already inspected) retire directly
            for lane in range(self.lanes):
                req = self.occupant[lane]
                if req is not None and self.dev_rem[lane] == 0:
                    outer._finish_async(self.eng, lane, req, self.writer)
                    self.occupant[lane] = None
        self._fill()

    def has_work(self) -> bool:
        return (bool(self.inflight) or bool(self.q)
                or any(o is not None for o in self.occupant))

    # --- synchronous fallback (--dispatch-depth off) ----------------------
    def run_sync(self) -> None:
        """The PR-3 shape, kept verbatim for debugging A/Bs: fetch every
        boundary as its chunk is dispatched (the fetch fences the whole
        chunk) and extract finished lanes on the scheduler thread. No
        pipelining, no tail programs."""
        outer = self.outer
        while self.has_work():
            if self._live_remaining():
                t0 = wall_clock()
                if self.idle_from is not None:
                    # device sat idle from the last fetch's return until
                    # this dispatch — the fence cost the A/B demonstrates
                    outer.device_idle_s += t0 - self.idle_from
                rem = self.eng.step_chunk()
                outer.boundary_wait_s += wall_clock() - t0
                outer.boundary_waits += 1
                outer.chunks_dispatched += 1
                self.idle_from = wall_clock()
                np.maximum(self.dev_rem - self.chunk, 0, out=self.dev_rem)
            else:
                rem = self.dev_rem
            for lane in range(self.lanes):
                req = self.occupant[lane]
                if req is not None and rem[lane] == 0:
                    outer._finish_sync(self.eng, lane, req, self.writer)
                    self.occupant[lane] = None
            self._fill()


class Engine:
    """Request-driven batched execution engine (library API).

    >>> eng = Engine(ServeConfig(lanes=4, chunk=8, buckets=(64,)))
    >>> rid = eng.submit(HeatConfig(n=32, ntime=100, dtype="float64"))
    >>> records = eng.results()   # drains the queue, returns all records

    ``submit`` only enqueues; ``run``/``results`` executes every admitted
    request to completion via dispatch-ahead continuous batching and
    returns the records in submit order.
    """

    def __init__(self, scfg: ServeConfig = ServeConfig()):
        self.scfg = scfg
        self._queues: Dict[BucketKey, collections.deque] = {}
        self._records: List[dict] = []
        self._by_id: Dict[str, dict] = {}
        self._seq = 0
        # one engine-wide lock: records are mutated and emitted from both
        # the scheduler thread and the SnapshotWriter thread — JSON lines
        # must not interleave mid-line and record mutation must not race
        self._lock = threading.Lock()
        # one compiled-program cache for the engine's lifetime: repeated
        # runs (a long-lived server draining wave after wave) never pay a
        # second (bucket, lane-tier) compile
        self._compiled: Dict = {}
        self.step_compiles = 0    # steady stepping programs built (the
                                  # criterion: at most one per
                                  # (bucket, lane-tier))
        self.tail_compiles = 0    # tail programs built (at most one per
                                  # (bucket, lane-tier), lazily)
        self.compile_s = 0.0
        # dispatch-ahead observability (summary()/cmd_serve surface these)
        self.chunks_dispatched = 0
        self.tail_chunks = 0
        self.boundary_waits = 0
        self.boundary_wait_s = 0.0   # host wall blocked on boundary fetches
        self.device_idle_s = 0.0     # est. device idle: per-group gaps with
                                     # nothing in flight at a boundary
        self.timing = None           # runtime.timing.Timing of the last run

    def _note_compile(self, k: int, seconds: float) -> None:
        if k == self.scfg.chunk:
            self.step_compiles += 1
        else:
            self.tail_compiles += 1
        self.compile_s += seconds

    # --- admission --------------------------------------------------------
    def submit(self, cfg: HeatConfig, request_id: Optional[str] = None) -> str:
        """Admit one request; returns its id. Unservable requests become
        status='rejected' records instead of raising (see module doc)."""
        rid = request_id or f"req-{self._seq:04d}"
        self._seq += 1
        if rid in self._by_id:
            raise ValueError(f"duplicate request id {rid!r}")
        rec = {"id": rid, "n": cfg.n, "ndim": cfg.ndim, "ntime": cfg.ntime,
               "dtype": cfg.dtype, "bc": cfg.bc, "status": "queued",
               "bucket": None, "lane": None, "queue_wait_s": None,
               "solve_s": None, "steps_per_s": None, "error": None}
        self._records.append(rec)
        self._by_id[rid] = rec
        if cfg.bc == "periodic":
            self._reject(rec, "unsupported-bc: periodic has no padded-lane "
                              "form (wraparound would wrap at the bucket "
                              "edge, not the request edge)")
            return rid
        b = _bucket_for(cfg, self.scfg.buckets)
        if b is None:
            self._reject(rec, f"bucket-overflow: request side {cfg.n} "
                              f"exceeds the biggest bucket "
                              f"{max(self.scfg.buckets)}")
            return rid
        key = BucketKey(ndim=cfg.ndim, n=b, dtype=cfg.dtype, bc=cfg.bc)
        rec["bucket"] = b
        self._queues.setdefault(key, collections.deque()).append(
            Request(id=rid, cfg=cfg, submit_t=wall_clock(), key=key))
        return rid

    def _reject(self, rec: dict, reason: str) -> None:
        with self._lock:
            rec["status"] = "rejected"
            rec["error"] = reason
        self._emit(rec)

    def _emit(self, rec: dict) -> None:
        """Emit one request record as a JSON line. Called from the
        scheduler thread (rejections) AND the writer thread (finishes);
        the lock keeps concurrent lines from interleaving mid-line and
        snapshots the record fields consistently."""
        if self.scfg.emit_records:
            with self._lock:
                json_record("serve_request",
                            **{k: v for k, v in rec.items() if k != "T"})

    # --- execution --------------------------------------------------------
    def run(self) -> List[dict]:
        """Drain every queued request through dispatch-ahead continuous
        batching; returns all records (submit order). Reentrant: new
        submits after a run are served by the next run against warm
        compiled programs."""
        from ..runtime.timing import Timing

        writer = async_io.SnapshotWriter()
        t0 = wall_clock()
        try:
            runners = [
                _GroupRunner(self, key, self._queues[key], writer)
                for key in list(self._queues) if self._queues[key]
            ]
            if self.scfg.dispatch_depth == 0:
                # synchronous debugging fallback: groups drain one at a
                # time with a fence at every boundary (the PR-3 shape)
                for r in runners:
                    r.run_sync()
            else:
                live = [r for r in runners if r.has_work()]
                while live:
                    # prime every group's device queue before anyone
                    # blocks: one group's boundary D2H + bookkeeping then
                    # hides under the other groups' queued compute
                    for r in live:
                        r.dispatch_fill()
                    nxt = []
                    for r in live:
                        r.process_boundary()
                        r.dispatch_fill()   # refilled lanes step while the
                                            # other groups take boundaries
                        if r.has_work():
                            nxt.append(r)
                    live = nxt
        finally:
            # every queued writeback lands (or fails per-request) before
            # results are reported; per-request jobs swallow their own
            # failures, so a surviving writer error here is a real bug
            writer.drain()
        wall = wall_clock() - t0
        self.timing = Timing(total_s=wall, solve_s=wall,
                             compile_s=self.compile_s,
                             dispatch_depth=self.scfg.dispatch_depth,
                             boundary_wait_s=round(self.boundary_wait_s, 6))
        return list(self._records)

    def results(self) -> List[dict]:
        """``run`` + records (the common library call)."""
        if any(self._queues.values()):
            self.run()
        return list(self._records)

    # --- lane retirement --------------------------------------------------
    def _finish_timing(self, req: Request) -> dict:
        rec = self._by_id[req.id]
        now = wall_clock()
        with self._lock:
            start = rec.pop("_start_t", now)
            rec["solve_s"] = round(now - start, 6)
            rec["steps_per_s"] = (round(req.cfg.ntime / (now - start), 3)
                                  if now > start else None)
        return rec

    def _writeback_job(self, rec: dict, req: Request, writer,
                       get_field) -> None:
        """Build + submit the writer-thread job for one finished request.
        ``get_field()`` produces the host field — under dispatch-ahead it
        performs the snapshot D2H *in the writer thread*; the sync
        fallback passes a host array already fetched."""
        cfg, scfg = req.cfg, self.scfg
        attempts = {"n": 0}

        def job():
            # Runs in the writer thread. Transient sink errors are
            # re-raised so the SnapshotWriter's bounded in-thread retry
            # (backoff, same budget as checkpoints) gets its shot; a final
            # failure is recorded on THIS request and swallowed — it must
            # not poison writer._exc and kill the other lanes' drain.
            attempts["n"] += 1
            try:
                T = get_field()
                plan = faults.plan_for(cfg)
                if plan is not None:
                    plan.sink_fault(cfg.ntime)
                path = (str(_write_result(scfg.out_dir, req.id, T, cfg))
                        if scfg.out_dir else None)
                with self._lock:
                    if scfg.keep_fields or not scfg.out_dir:
                        rec["T"] = T
                    if path is not None:
                        rec["path"] = path
                    rec["status"] = "ok"
            except BaseException as e:  # noqa: BLE001 — per-request record
                if async_io.is_transient(e) and attempts["n"] <= writer.retries:
                    raise
                with self._lock:
                    rec["status"] = "error"
                    rec["error"] = f"{type(e).__name__}: {e}"
            self._emit(rec)

        writer.submit(job)

    def _finish_async(self, eng: LaneEngine, lane: int, req: Request,
                      writer) -> None:
        """Dispatch-ahead retirement: take a one-lane ON-DEVICE snapshot
        (enqueued behind the in-flight chunks; the scheduler thread never
        blocks) and move the D2H + writeback wholly into the writer."""
        rec = self._finish_timing(req)
        snap = eng.snapshot_lane(lane)
        n = req.cfg.n
        self._writeback_job(rec, req, writer, lambda: eng.extract(snap, n))

    def _finish_sync(self, eng: LaneEngine, lane: int, req: Request,
                     writer) -> None:
        """Sync-fallback retirement: fetch the lane on the scheduler
        thread (fences every chunk in flight), write back in the writer."""
        rec = self._finish_timing(req)
        T = eng.extract_lane(lane, req.cfg.n)
        self._writeback_job(rec, req, writer, lambda: T)

    # --- reporting --------------------------------------------------------
    def summary(self) -> dict:
        by_status = collections.Counter(r["status"] for r in self._records)
        return {"requests": len(self._records), **dict(by_status),
                "step_compiles": self.step_compiles,
                "tail_compiles": self.tail_compiles,
                "compile_s": round(self.compile_s, 3),
                "dispatch_depth": self.scfg.dispatch_depth,
                "chunks_dispatched": self.chunks_dispatched,
                "tail_chunks": self.tail_chunks,
                "boundary_waits": self.boundary_waits,
                "boundary_wait_s": round(self.boundary_wait_s, 6),
                "device_idle_s": round(self.device_idle_s, 6)}
