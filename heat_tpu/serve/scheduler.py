"""Admission queue + shape bucketing + dispatch-ahead continuous batching.

The serving contract, in the shape of an inference server's scheduler:

- **Admission**: ``Engine.submit(cfg)`` validates a request against the
  bucket table and enqueues it. A request the engine cannot serve (side
  larger than the biggest bucket; periodic BC, which has no padded-lane
  form) is *rejected as a record*, never as an engine error — multi-tenant
  serving must not let one bad request take down the queue.
- **Bucketing**: requests are grouped by ``BucketKey`` (ndim, smallest
  bucket side that fits, dtype, BC). One group = one stacked lane array =
  at most one stepping-program compile per (bucket, lane-tier) no matter
  how many requests flow through it — lane counts round UP to power-of-two
  tiers (``engine.lane_tier``) so uneven waves share programs.
- **Continuous batching, dispatch-ahead**: the chunk loop never stops for
  a single lane, and (the PR-4 rework) the device never waits on the host
  between chunks. The scheduler keeps ``dispatch_depth`` chunk programs in
  flight per group and inspects the remaining-step vector of the OLDEST
  one — fetched while the newer chunks compute behind it, so the
  boundary's D2H and python bookkeeping overlap device work instead of
  fencing it. Finished lanes take a one-lane on-device snapshot
  (``runtime/async_io.lane_snapshot``) and stepping resumes immediately;
  the D2H + result write happen wholly in the ``SnapshotWriter`` thread.
  ``Engine.run`` round-robins chunk dispatch across all live bucket
  groups, so one group's boundary bookkeeping hides under another group's
  compute. ``dispatch_depth=0`` is the fully synchronous debugging
  fallback (fetch-every-boundary, extraction on the scheduler thread —
  the PR-3 shape).
- **Determinism of the boundary**: the device decrements each lane's
  remaining count by exactly one per step while positive, so the host
  mirrors the countdown and PREDICTS every chunk's post-step vector at
  dispatch time. Prediction drives dispatch policy (is another chunk
  useful; steady chunk vs tail); the fetched vector stays the ground
  truth for finishing lanes — and must equal the prediction, enforced
  per boundary (a divergence means the masking contract broke, and a
  serving engine must never silently mis-serve). Lanes whose occupant
  was swapped in after a chunk was dispatched are guarded by a per-lane
  epoch: a stale in-flight chunk cannot "finish" the new occupant.
- **Tail chunks**: when every live lane's remaining count has dropped
  below the chunk (and far enough that it saves compute), the group
  dispatches a lazily-precompiled quarter-chunk tail program instead of
  a mostly-masked full chunk — at most ONE extra compile per
  (bucket, lane-tier), waste bounded by the tail size.
- **Fault isolation**: an injected or real sink failure on one request's
  writeback (``sink-error`` in runtime/faults.py grammar) fails THAT
  request's record; transient errors still ride the writer's bounded
  in-thread retry, and the engine keeps draining the other lanes either
  way.
- **Per-lane fault domains** (ISSUE 5): every chunk boundary carries a
  per-lane ``isfinite`` bit next to the remaining-step vector (computed
  on device, fetched in the boundary D2H the scheduler already pays —
  serve/engine.py). A flagged lane is **quarantined**: its record fails
  with a structured ``nonfinite`` status and approximate step, the lane
  is freed for the admission queue, and every other lane continues
  bit-identically (the masking contract confines a NaN to its own lane).
  ``--serve-on-nan rollback`` instead mirrors ``drive()``'s per-solve
  contract per lane: each dispatched chunk keeps an on-device snapshot
  of its post-chunk stack, a lane judged finite at a boundary promotes
  that snapshot row to its last-good state, and a flagged lane is
  restored and re-stepped alone — transient poison recovers
  bit-identically, a deterministic blow-up re-flags and is quarantined
  after a bounded retry budget. Requests may carry a ``deadline_ms``
  (engine default ``--serve-deadline``); an over-deadline lane is
  preempted at its next boundary with status ``deadline`` and still-
  queued requests past their deadline are shed without ever occupying a
  lane. ``--max-queue`` bounds admission (excess requests get a
  structured ``overloaded`` rejection instead of an unbounded queue),
  and the boundary fetch runs under a watchdog (``--fetch-watchdog``):
  a wedged device fetch fails that group's in-flight and queued
  requests cleanly instead of hanging ``heat-tpu serve`` forever.
  Freed-but-unreplaced lanes keep counting down on device (masked,
  garbage-stepping at worst) so the host countdown mirror — and the
  desync cross-check — stay exact without an extra device program.

- **Lane-kernel selection** (ISSUE 9): each bucket group resolves
  ``ServeConfig.lane_kernel`` (``--serve-lane-kernel auto|pallas|xla``)
  through ``engine.resolve_lane_kernel`` — the multi-lane Pallas kernels
  where available (auto: on TPU), the vmapped XLA oracle elsewhere. A
  requested-but-unavailable Pallas bucket degrades to XLA as a
  per-(bucket, tier) structured ``lane_kernel_fallback`` record +
  counter + /metrics gauge, never an error. Rollback mode additionally
  builds its engines ``donate=False``: each dispatched chunk's
  undonated input stack IS the previous boundary's snapshot, so
  keeping boundaries restorable costs no standalone copy program on
  the dispatch path.

- **Two-tier placement** (ISSUE 10): a request whose side overflows
  every bucket no longer dies as a ``bucket-overflow`` rejection — on a
  multi-device host it is admitted to the engine-wide mega queue and
  runs as a **sharded mega-lane**: one request occupying the whole
  device mesh via the ``backends/sharded.py`` padded-carry chunked
  advance (``MegaLaneRunner`` + ``serve/engine.py MegaLaneEngine``),
  under the same dispatch-ahead contract as the packed lanes (boundary
  handle, dispatch depth, countdown mirror, isfinite bit, deadline /
  quarantine / rollback / watchdog — one mega-lane is a fault domain of
  size one-mesh). ``Engine.run``'s round-robin treats mega slots as
  just more groups, so packed traffic and a resident mega-lane hide
  each other's boundary bookkeeping. ``--mega-lanes N`` gates the tier
  (auto: 1 on a multi-device mesh, 0 single-device where overflow stays
  a rejection — bit-identical to the pre-mega engine); every record,
  cost-model row, /metrics gauge, usage stamp, and trace row carries a
  ``placement=packed|mega`` dimension.

Per-request structured JSON records (queue wait, steps/s, lane id) go
through ``runtime/logging``; each request also keeps a python-level record
for library callers (``Engine.results()``). Records are mutated from both
the scheduler thread and the writer thread — one engine-wide lock guards
every record mutation and every ``json_record`` emission so JSON lines
cannot interleave mid-line.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from ..config import (DEFAULT_SLO_CLASS, DEFAULT_TENANT, LANE_KERNELS,
                      SLO_TARGETS, HeatConfig, validate_slo_fields,
                      validate_until_fields)
from ..grid import ic_envelope, initial_condition
from ..runtime import async_io, faults
from ..runtime import checkpoint as ckpt_mod
from ..runtime import convergence as conv_mod
from ..runtime import debug as debug_mod
from ..runtime import numerics as numerics_mod
from ..runtime import prof as prof_mod
from ..runtime import trace as trace_mod
from ..runtime.logging import json_record, master_print
from . import policy as policy_mod
from . import solvecache as solvecache_mod
from .engine import (BucketKey, LaneEngine, MegaLaneEngine, lane_tier,
                     resolve_lane_kernel, unpack_boundary, wall_clock)
from .engine import fetch_boundary as engine_fetch_boundary

# Statuses a record can never leave: what poll()/wait() callers and the
# gateway's streaming responses key on.
TERMINAL_STATUSES = ("ok", "rejected", "error", "nonfinite", "deadline")


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine-level knobs (the per-request physics lives in HeatConfig)."""

    lanes: int = 4            # max concurrent requests per bucket group
                              # (waves round up to power-of-two tiers
                              # capped here — see engine.lane_tier)
    chunk: int = 16           # steps per device program call (the swap
                              # granularity of continuous batching)
    buckets: tuple = (256, 512, 1024)  # grid-side buckets; a request is
                              # padded up to the smallest side that fits
    dispatch_depth: int = 2   # chunk programs kept in flight per group
                              # before the scheduler blocks on a boundary
                              # fetch; 1 = fetch the chunk just dispatched
                              # (pipelined bookkeeping only), 0 = fully
                              # synchronous fallback for debugging (the
                              # PR-3 fence-every-chunk shape, extraction
                              # on the scheduler thread)
    out_dir: Optional[str] = None  # writeback directory (<id>.npz); None =
                              # results kept in-memory on the records
    keep_fields: bool = False  # keep final fields on records even when
                              # writing files (tests / library callers)
    emit_records: bool = True  # print one JSON line per finished request
    on_nan: str = "fail"      # a lane whose boundary finite bit drops:
                              # "fail" quarantines the request (structured
                              # nonfinite record, lane freed); "rollback"
                              # restores the lane's last verified-finite
                              # boundary snapshot and re-steps only that
                              # lane (bounded retries — deterministic
                              # blow-ups still quarantine)
    deadline_ms: Optional[float] = None  # engine-default per-request wall
                              # budget from submit; a request's own
                              # deadline_ms overrides. Over-deadline lanes
                              # preempt at their next chunk boundary
                              # (status "deadline"); None = no deadline
    max_queue: Optional[int] = None  # admission bound: submits beyond this
                              # many queued requests are shed with a
                              # structured "overloaded" rejection;
                              # None/0 = unbounded
    fetch_timeout_s: Optional[float] = 600.0  # boundary-fetch watchdog: a
                              # boundary D2H exceeding this fails the
                              # group's requests cleanly instead of
                              # hanging the serve loop (None = off; the
                              # default mirrors the writer drain bound)
    inject: str = ""          # engine-scoped fault spec (runtime/faults.py
                              # grammar incl. the serve kinds lane-nan /
                              # fetch-hang); per-request specs ride each
                              # request's own "inject" key
    policy: str = "fifo"      # admission ordering (serve/policy.py):
                              # "fifo" = submit order (bit-identical to
                              # the pre-policy engine), "edf" = SLO-class
                              # priority + earliest-deadline-first within
                              # a class, "fair" = weighted fair share
                              # across tenants with EDF inside each
    tenant_weights: tuple = ()  # (("name", weight), ...) fair-share
                              # weights; unlisted tenants weigh 1.0
    tenant_quota: Optional[int] = None  # per-tenant admission sub-quota:
                              # one tenant may hold at most this many
                              # queued requests (structured "overloaded"
                              # rejection past it) — the flood guard
                              # --max-queue alone cannot give, because a
                              # single tenant can fill a shared bound;
                              # None/0 = no per-tenant bound
    trace: Optional[str] = None  # export the run's event ring as Chrome
                              # trace-event JSON here at drain (Perfetto /
                              # chrome://tracing); None = flight-recorder
                              # only (ring retained, dumped on faults)
    trace_buffer: int = trace_mod.DEFAULT_BUFFER  # event-ring capacity
                              # (runtime/trace.py); 0 disables recording
                              # entirely — including the flight recorder
    flight_dir: Optional[str] = None  # flight-recorder dump directory
                              # (flightrec-<ts>.trace.json on watchdog /
                              # quarantine-after-rollbacks / scheduler
                              # crash); None = out_dir. With neither set
                              # the dump is skipped (never the cwd — the
                              # ring is retained in memory either way)
    prof: bool = True         # the performance & cost observatory
                              # (runtime/prof.py): online chunk-cost
                              # model, per-tenant usage ledger, memory
                              # watermarks, SLO burn-rate monitor — fed
                              # from timestamps the scheduler already
                              # takes. off = aggregation/model/sampling
                              # disabled (records keep their usage
                              # stamps so the schema never flickers);
                              # the A/B baseline of
                              # benchmarks/prof_overhead_lab.py
    slo_targets: tuple = ()   # (("class", target), ...) per-class SLO
                              # target overrides (deadline-hit fraction;
                              # defaults config.SLO_TARGETS) — the burn
                              # monitor's error budget is 1 - target
    slo_burn_threshold: float = prof_mod.SLO_BURN_THRESHOLD
                              # emit a structured slo_alert when a
                              # class's FAST and SLOW windows both burn
                              # budget above this multiple of the
                              # sustainable rate
    slo_fast_window_s: float = prof_mod.SLO_FAST_WINDOW_S
    slo_slow_window_s: float = prof_mod.SLO_SLOW_WINDOW_S
    mem_poll_every: int = prof_mod.MEM_POLL_EVERY_DEFAULT
                              # chunk boundaries between device-memory
                              # watermark samples (leak sentinel);
                              # 0 = never sample
    mega_lanes: Optional[int] = None  # second placement tier (ISSUE 10):
                              # how many mesh-spanning sharded mega-lanes
                              # may run concurrently. A request whose side
                              # overflows every bucket is admitted to the
                              # mega queue instead of rejected and runs as
                              # ONE request occupying the whole device
                              # mesh (backends/sharded.py chunked advance
                              # under the same dispatch-ahead contract).
                              # None = auto: 1 when the host has > 1
                              # device, 0 on single-device hosts where
                              # overflow stays a rejection; 0 restores
                              # the pre-mega behavior bit-identically
    lane_kernel: str = "auto"  # chunk-program body per bucket
                              # (--serve-lane-kernel): "auto" = the
                              # multi-lane Pallas kernels on TPU wherever
                              # the bucket has a kernel plan, the vmapped
                              # XLA stencil elsewhere; "pallas"/"xla"
                              # force it. An unavailable Pallas bucket is
                              # a per-(bucket, tier) structured
                              # lane_kernel_fallback record + counter,
                              # never an error; the XLA program stays the
                              # bit-exactness oracle (engine.py
                              # resolve_lane_kernel)
    numerics: bool = True     # the numerics observatory (runtime/
                              # numerics.py, ISSUE 15): per-lane solution-
                              # quality detectors fed from the stats rows
                              # the chunk programs ALWAYS fuse into the
                              # boundary vector. off = host-side ingestion
                              # disabled only — the device programs are
                              # identical either way, so results stay
                              # byte-identical on vs off (the A/B of
                              # benchmarks/numerics_overhead_lab.py)
    steady_tol: float = 1e-12  # steady-state detector (--steady-tol): a
                              # lane whose final-mini-step residual EWMA
                              # sits below this while steps remain emits
                              # ONE steady_state record per request; for
                              # until=steady requests (per-request "tol"
                              # overrides this default) the scheduler
                              # also ACTS on it — the lane retires at its
                              # dispatch frontier with exit=steady
                              # (semantic scheduling, ISSUE 16)
    numerics_guard: str = "warn"  # violation routing (--numerics-guard):
                              # "warn" = structured numerics_violation
                              # record + flight dump only; "quarantine" =
                              # additionally take the PR-5 quarantine
                              # exit — the request fails nonfinite, the
                              # lane frees, co-scheduled lanes continue
                              # byte-identically
    engine_ckpt_interval: int = 0  # zero-downtime serving (ISSUE 17):
                              # checkpoint the whole engine state (lane
                              # fields + occupancy/queue/usage manifest)
                              # every N processed chunk boundaries, and
                              # always at drain; ``serve --resume DIR``
                              # reconstructs the engine from the newest
                              # valid generation. 0 = off (no manifest is
                              # ever written — bit-identical to PR 16)
    engine_ckpt_dir: Optional[str] = None  # manifest + lane-field
                              # directory; None = <out_dir>/engine-ckpt,
                              # or ./engine-ckpt with no out_dir
    cache: bool = False       # two-level solve cache (ISSUE 19): consult
                              # the content-addressed result store at
                              # submit — a full hit replays the stored
                              # npz byte-identically without occupying a
                              # lane (billed usage.cached, zero
                              # lane_s/steps); a prefix hit seeds the
                              # lane from the deepest shallower entry
                              # and steps only the delta — and publish
                              # every ok result + chunk-boundary lane
                              # snapshot back into it. Off (default) is
                              # bit-identical to pre-cache behavior:
                              # no directory is ever touched
    cache_dir: Optional[str] = None  # entry directory (shared across a
                              # fleet on shared storage); None =
                              # <out_dir>/solve-cache, or ./solve-cache
                              # with no out_dir
    cache_max_bytes: int = 0  # LRU-evict oldest entries once total
                              # entry bytes exceed this (0 = unbounded)

    def __post_init__(self):
        if self.lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {self.lanes}")
        if self.chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {self.chunk}")
        if self.dispatch_depth < 0:
            raise ValueError(f"dispatch_depth must be >= 0 (0 = sync "
                             f"fallback), got {self.dispatch_depth}")
        if not self.buckets or any(b < 3 for b in self.buckets):
            raise ValueError(f"buckets must be sides >= 3, got {self.buckets}")
        if self.on_nan not in ("fail", "rollback"):
            raise ValueError(f"on_nan must be 'fail' or 'rollback', "
                             f"got {self.on_nan!r}")
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ValueError(f"deadline_ms must be > 0 (None = no "
                             f"deadline), got {self.deadline_ms}")
        if self.max_queue is not None and self.max_queue < 0:
            raise ValueError(f"max_queue must be >= 0 (None/0 = "
                             f"unbounded), got {self.max_queue}")
        if self.fetch_timeout_s is not None and self.fetch_timeout_s <= 0:
            raise ValueError(f"fetch_timeout_s must be > 0 (None = no "
                             f"watchdog), got {self.fetch_timeout_s}")
        if self.policy not in policy_mod.POLICIES:
            raise ValueError(f"policy must be one of {policy_mod.POLICIES}, "
                             f"got {self.policy!r}")
        for entry in self.tenant_weights:
            name, weight = entry
            validate_slo_fields(name, None)
            if not float(weight) > 0:
                raise ValueError(f"tenant weight must be > 0, got "
                                 f"{name}={weight}")
        if self.tenant_quota is not None and self.tenant_quota < 0:
            raise ValueError(f"tenant_quota must be >= 0 (None/0 = "
                             f"unbounded), got {self.tenant_quota}")
        if self.trace_buffer < 0:
            raise ValueError(f"trace_buffer must be >= 0 (0 disables "
                             f"recording), got {self.trace_buffer}")
        if self.trace and self.trace_buffer == 0:
            raise ValueError("trace export needs trace_buffer > 0 (the "
                             "export is the event ring's contents)")
        for entry in self.slo_targets:
            cls, target = entry
            validate_slo_fields(None, cls)
            if not 0.0 < float(target) < 1.0:
                raise ValueError(f"SLO target must be in (0, 1), got "
                                 f"{cls}={target}")
        if self.slo_burn_threshold <= 0:
            raise ValueError(f"slo_burn_threshold must be > 0, got "
                             f"{self.slo_burn_threshold}")
        if self.slo_fast_window_s <= 0 or self.slo_slow_window_s <= 0:
            raise ValueError("SLO burn windows must be > 0 seconds, got "
                             f"{self.slo_fast_window_s}/"
                             f"{self.slo_slow_window_s}")
        if self.mem_poll_every < 0:
            raise ValueError(f"mem_poll_every must be >= 0 (0 = never "
                             f"sample), got {self.mem_poll_every}")
        if self.lane_kernel not in LANE_KERNELS:
            raise ValueError(f"lane_kernel must be one of {LANE_KERNELS}, "
                             f"got {self.lane_kernel!r}")
        if self.mega_lanes is not None and self.mega_lanes < 0:
            raise ValueError(f"mega_lanes must be >= 0 (None = auto: 1 on "
                             f"a multi-device mesh, 0 single-device), got "
                             f"{self.mega_lanes}")
        if not self.steady_tol > 0:
            raise ValueError(f"steady_tol must be > 0, got "
                             f"{self.steady_tol}")
        if self.numerics_guard not in ("warn", "quarantine"):
            raise ValueError(f"numerics_guard must be 'warn' or "
                             f"'quarantine', got {self.numerics_guard!r}")
        if self.engine_ckpt_interval < 0:
            raise ValueError(f"engine_ckpt_interval must be >= 0 (0 = "
                             f"off), got {self.engine_ckpt_interval}")
        if self.cache_max_bytes < 0:
            raise ValueError(f"cache_max_bytes must be >= 0 (0 = "
                             f"unbounded), got {self.cache_max_bytes}")
        if self.inject:
            # fail at construction, not at a boundary mid-drain (same
            # parse-time contract as HeatConfig.inject)
            faults.parse_spec(self.inject)


# --serve-on-nan rollback: restores a flagged lane at most this many times
# per request before declaring the blow-up deterministic — the per-lane
# mirror of backends/common.py's _MAX_ROLLBACKS_PER_STEP contract.
_MAX_LANE_ROLLBACKS = 2


def mega_device_count() -> int:
    """Devices a mega-lane mesh could span on THIS host — the seam the
    auto ``--mega-lanes`` default and the overflow rejection text resolve
    through (tests fake a single-device host by patching it)."""
    import jax

    return len(jax.devices())


@dataclasses.dataclass
class Request:
    """One admitted solve request."""

    id: str
    cfg: HeatConfig
    submit_t: float
    key: Optional[BucketKey] = None   # None once rejected, and for
                                      # mega-placed requests (their
                                      # "bucket" is the device mesh)
    placement: str = "packed"         # "packed" (vmapped bucket lanes) |
                                      # "mega" (mesh-spanning sharded
                                      # lane) — the ISSUE-10 second tier
    deadline_t: Optional[float] = None  # absolute wall deadline (engine
                                        # clock), resolved at submit from
                                        # the request's deadline_ms or the
                                        # engine default; None = none
    tenant: str = DEFAULT_TENANT        # fair-share / quota accounting key
    slo_class: str = DEFAULT_SLO_CLASS  # SLO class (config.SLO_CLASSES)
    seq: int = 0                        # engine-wide submit counter: the
                                        # FIFO order and every policy's
                                        # deterministic tiebreak
    trace_id: str = ""                  # request-scoped trace/flow id
                                        # (runtime/trace.py), minted at
                                        # submit and echoed in the record
    until: str = "steps"                # completion semantics (config.
                                        # UNTIL_MODES): "steps" runs all
                                        # ntime steps bit-for-bit as
                                        # before; "steady" retires at the
                                        # first chunk boundary whose
                                        # residual EWMA passes tolerance
    tol: Optional[float] = None         # per-request steady tolerance
                                        # (until=steady only; None = the
                                        # engine-wide --steady-tol)
    predicted_steps: Optional[int] = None  # closed-form eigenmode ETA to
                                        # steady, minted at submit
                                        # (runtime/convergence.py): the
                                        # EDF predicted-finish rank and
                                        # the trace's predicted-vs-actual
                                        # retirement boundary
    restore: Optional[dict] = None      # engine-state resume payload
                                        # (serve/resume.py): the
                                        # checkpointed host field ("T"),
                                        # "remaining", the cumulative
                                        # "chunks" meter, and the saved
                                        # "numerics" detector state; the
                                        # admitting _fill consumes it —
                                        # None for every normal request


def _bucket_for(cfg: HeatConfig, buckets) -> Optional[int]:
    """Smallest bucket side that fits the request, or None (overflow)."""
    for b in sorted(buckets):
        if cfg.n <= b:
            return b
    return None


def _write_result(out_dir, req_id: str, T: np.ndarray, cfg: HeatConfig,
                  steps: Optional[int] = None):
    """Atomic-publish one request's final field (same torn-file discipline
    as runtime/checkpoint.py: temp name outside any discovery glob).
    ``steps`` is the step count the field actually carries — below
    ``cfg.ntime`` for a steady early exit."""
    from pathlib import Path

    d = Path(out_dir)
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"{req_id}.npz"
    tmp = d / (path.name + ".tmp")
    with open(tmp, "wb") as f:
        np.savez_compressed(f, T=np.asarray(T),
                            step=cfg.ntime if steps is None else int(steps),
                            n=cfg.n, ndim=cfg.ndim, dtype=cfg.dtype)
    tmp.rename(path)
    return path


class _GroupRunner:
    """Dispatch-ahead continuous batching for ONE bucket group.

    Owns the group's ``LaneEngine``, occupancy, the host-side countdown
    mirror (``dev_rem`` — exact, because the device decrements remaining
    by one per step while positive), and the in-flight deque of
    ``(seq, remaining-handle, predicted-vector)`` chunk boundaries.
    ``Engine.run`` drives many runners round-robin; each tick dispatches
    until ``dispatch_depth`` chunks are queued, then takes at most one
    boundary (the oldest handle).
    """

    def __init__(self, outer: "Engine", key: BucketKey, q,
                 writer: "async_io.SnapshotWriter"):
        self.outer = outer
        self.key = key
        self.q = q
        self.writer = writer
        scfg = outer.scfg
        self.chunk = scfg.chunk
        self.depth = max(1, scfg.dispatch_depth)
        self.rollback = scfg.on_nan == "rollback"
        self.lanes = lane_tier(min(len(q), scfg.lanes), scfg.lanes)
        # per-bucket kernel resolution (--serve-lane-kernel): a requested
        # Pallas program this bucket cannot run degrades loudly to the
        # XLA oracle — structured record + counter, never an error.
        # Rollback mode drops donation so in-flight boundary snapshots
        # are plain references to the undonated input stacks (no per-
        # chunk copy program on the dispatch path — engine.snapshot_stack)
        self.kernel, self._kernel_fb = resolve_lane_kernel(
            scfg.lane_kernel, key)
        self.eng = LaneEngine(key, self.lanes, scfg.chunk,
                              compiled_cache=outer._compiled,
                              on_compile=outer._note_compile,
                              kernel=self.kernel,
                              donate=not self.rollback)
        if self._kernel_fb is not None:
            outer._note_lane_fallback(key, self.lanes, self._kernel_fb)
        self.occupant: List[Optional[Request]] = [None] * self.lanes
        # first dispatch seq whose chunk covers the lane's CURRENT
        # occupant: an in-flight chunk older than the epoch shows the
        # PREVIOUS occupant's state (zeros, or a quarantined NaN field)
        # and must not finish — or re-flag — the new one
        self.epoch = [0] * self.lanes
        self.dev_rem = np.zeros(self.lanes, dtype=np.int64)
        # per-lane fault-domain state, (re)set at each admission:
        # pending lane-nan poison thresholds, rollback retries left, and
        # the last verified-finite boundary (stack snapshot, steps left)
        self.nan_pending: List[List[int]] = [[] for _ in range(self.lanes)]
        # pending (step, eps) perturb events — the numerics observatory's
        # chaos channel (finite bump, so the isfinite bit never drops)
        self.perturb_pending: List[List[tuple]] = [
            [] for _ in range(self.lanes)]
        self.rb_left = [0] * self.lanes
        self.last_good: List[Optional[tuple]] = [None] * self.lanes
        # semantic scheduling (ISSUE 16): remaining-at-detection for a
        # lane whose until=steady occupant passed tolerance this
        # boundary; the judge pass consumes it (same process_boundary
        # call — _ingest_numerics runs first) and retires the lane at
        # its dispatch frontier
        self.steady_exit: List[Optional[int]] = [None] * self.lanes
        self.seq = 0                        # next dispatch's sequence id
        self.inflight: collections.deque = collections.deque()
        self.idle_from: Optional[float] = None  # group device queue empty
                                                # since (boundary gaps only)
        # cost-observatory feed (runtime/prof.py): the model key names the
        # bucket geometry; per-lane chunk counters back the usage stamps
        # (one vectorized add per dispatch — no per-lane python loop, no
        # device work); last_fetch_t makes the boundary service-time
        # estimator exact under pipelining (see prof.CostModel)
        self.cost_label = f"{key.ndim}d/n{key.n}/{key.dtype}/{key.bc}"
        self.lane_chunks = np.zeros(self.lanes, dtype=np.int64)
        self.last_fetch_t: Optional[float] = None
        self.allow_growth = False   # online loop opts in: offline run()
                                    # sizes runners from the full queue,
                                    # so growth (and its pipeline drain)
                                    # must never perturb the batch shape
        # trace tracks (runtime/trace.py): one process row per bucket
        # group, one thread row per lane (the occupancy timeline) plus a
        # dispatch row for chunk-in-flight / device-idle spans. Registered
        # here, once, so the per-event path is append-only.
        self.tracer = outer.tracer
        self.track_name = (f"lanes {key.ndim}d n{key.n} "
                           f"{key.dtype} {key.bc}")
        self.group_track = self.tracer.track(self.track_name, "dispatch")
        self.lane_tracks = [self.tracer.track(self.track_name, f"lane {i}")
                            for i in range(self.lanes)]
        self._fill()

    # --- admission into lanes --------------------------------------------
    def _fill(self) -> None:
        """Swap queued requests into every free lane (continuous
        batching). The IC build + H2D load run on the scheduler thread,
        but with chunks in flight they overlap device compute instead of
        extending a fence. Queued requests already past their deadline
        are shed here — failing fast beats occupying a lane for a result
        nobody is waiting for. Pops happen under the engine lock (the
        gateway's HTTP threads push concurrently); which request pops is
        the admission policy's call (serve/policy.py), recorded in
        ``Engine.admission_trace``."""
        outer = self.outer
        if outer._ckpt_pause:
            # checkpoint bubble: no new admissions while the engine is
            # draining its pipeline toward the consistent cut — queued
            # requests are part of the manifest, not of a lane
            return
        for lane in range(self.lanes):
            while self.occupant[lane] is None and self.q:
                with outer._lock:
                    req = self.q.pop()
                    if req is None:
                        break
                    outer._queued_by_tenant[req.tenant] -= 1
                    outer.admission_trace.append(req.id)
                now = wall_clock()
                tr = self.tracer
                if tr.enabled:
                    # queue-wait span (pop side — serve/policy.py): the
                    # request's wait under THIS policy, id-paired so
                    # overlapping waits of one tenant render cleanly
                    policy_mod.note_pop(tr, outer.scfg.policy, req, now)
                cut = outer._deadline_cut(req, now)
                if cut is not None:
                    if tr.enabled:
                        tr.instant("deadline-shed", self.group_track,
                                   trace_id=req.trace_id,
                                   args={"id": req.id}, ts=now)
                    outer._fail_request(
                        req, "deadline",
                        "deadline: cancelled (deadline-preemption) while "
                        "still queued (never admitted)"
                        if cut == "cancelled" else
                        f"deadline: exceeded its "
                        f"{1e3 * (req.deadline_t - req.submit_t):.0f} ms "
                        f"budget while still queued (never admitted)")
                    outer.deadline_misses += 1
                    continue
                if tr.enabled:
                    tr.flow("t", self.lane_tracks[lane], req.trace_id,
                            ts=now)
                rec = outer._by_id[req.id]
                with outer._lock:
                    rec["lane"] = lane
                    rec["queue_wait_s"] = round(now - req.submit_t, 6)
                    rec["status"] = "running"
                    rec["_start_t"] = now
                rst = req.restore
                if rst:
                    # engine-state resume (serve/resume.py): re-seed the
                    # lane from the checkpointed field at its last
                    # boundary — the maybe_grow transplant contract, so
                    # continuation is bit-identical to an uninterrupted
                    # run. The chunk meter continues where it stopped:
                    # usage stamps stay cumulative across incarnations.
                    req.restore = None
                    self.eng.load_lane(lane, rst["T"], float(req.cfg.r),
                                       int(rst["remaining"]),
                                       req.cfg.bc_value)
                    self.dev_rem[lane] = int(rst["remaining"])
                    self.lane_chunks[lane] = int(rst.get("chunks", 0))
                else:
                    T0 = initial_condition(req.cfg)
                    self.eng.load_lane(lane, T0, float(req.cfg.r),
                                       req.cfg.ntime, req.cfg.bc_value)
                    self.dev_rem[lane] = req.cfg.ntime
                    self.lane_chunks[lane] = 0   # usage meter restarts
                                                 # with the new occupant
                self.occupant[lane] = req
                self.epoch[lane] = self.seq
                self.nan_pending[lane] = outer._lane_nan_steps(req)
                self.perturb_pending[lane] = outer._lane_perturb_events(req)
                if self.nan_pending[lane] or self.perturb_pending[lane]:
                    outer._has_lane_faults = True  # gates _maybe_poison
                self.rb_left[lane] = _MAX_LANE_ROLLBACKS
                self.last_good[lane] = None
                self.steady_exit[lane] = None   # never inherit a prior
                                                # occupant's verdict
                if outer.numerics is not None:
                    # arm the detectors: the analytic IC/BC envelope (zero
                    # device work, zero host scans — grid.ic_envelope),
                    # plus the request's steady tolerance and the closed-
                    # form eigenmode rate seeding the ETA fuser
                    lo, hi = ic_envelope(req.cfg)
                    outer.numerics.admit(
                        req.id, lo, hi, req.cfg.dtype, steady_tol=req.tol,
                        log_rate=conv_mod.closed_form_log_rate(req.cfg))
                    if rst and rst.get("numerics"):
                        # resume continuity: EWMAs, fired latches, and
                        # the ETA fuser pick up where the checkpointed
                        # incarnation left them (until=steady lanes keep
                        # their convergence history)
                        outer.numerics.reseed(req.id, rst["numerics"])

    def _live_remaining(self) -> List[int]:
        return [int(self.dev_rem[i]) for i, o in enumerate(self.occupant)
                if o is not None and self.dev_rem[i] > 0]

    def _effective_remaining(self) -> List[int]:
        """Per-live-lane remaining WORK for tail sizing: the countdown
        mirror, tightened for ``until=steady`` occupants by the fused
        eigenmode/observed ETA (runtime/convergence.py via the numerics
        observatory). Prediction only moves the full-chunk -> tail-
        program switch earlier — same two compiled chunk sizes — and
        never changes results: a mispredicted lane just keeps taking
        tail chunks until its actual exit."""
        numerics = self.outer.numerics
        out = []
        for i, req in enumerate(self.occupant):
            rem = int(self.dev_rem[i])
            if req is None or rem <= 0:
                continue
            if req.until == "steady" and numerics is not None:
                eta = numerics.eta_steps(req.id)
                if eta is not None:
                    rem = min(rem, max(int(eta), 1))
            out.append(rem)
        return out

    # --- dispatch side ----------------------------------------------------
    def _maybe_poison(self) -> None:
        """lane-nan chaos: poison any occupied lane whose completed-step
        count (by the host countdown mirror, i.e. after every chunk
        already dispatched) has reached a pending threshold. Only ever
        called with an active fault plan — the no-fault hot path never
        touches this."""
        for lane, req in enumerate(self.occupant):
            if req is None or not (self.nan_pending[lane]
                                   or self.perturb_pending[lane]):
                continue
            done = req.cfg.ntime - int(self.dev_rem[lane])
            while self.nan_pending[lane] and done >= self.nan_pending[lane][0]:
                self.nan_pending[lane].pop(0)   # fire-once per request
                self.eng.poison_lane(lane, req.cfg.n)
            while (self.perturb_pending[lane]
                   and done >= self.perturb_pending[lane][0][0]):
                _, eps = self.perturb_pending[lane].pop(0)  # fire-once
                self.eng.perturb_lane(lane, req.cfg.n, eps)

    def dispatch_fill(self) -> None:
        """Queue chunk programs until ``dispatch_depth`` are in flight or
        no lane has steps left to run. Pure host->device enqueue: no
        fetch, no fence (a rollback-mode stack snapshot is a device-side
        copy, also enqueued without a fence)."""
        if self.outer._ckpt_pause:
            # checkpoint bubble: stop feeding the pipeline so the
            # in-flight chunks drain to the empty cut (_ckpt_tick)
            return
        poison = self.outer._has_lane_faults
        while len(self.inflight) < self.depth:
            if self.allow_growth and self._growth_wanted():
                # stop feeding the pipeline: once the in-flight chunks
                # drain, maybe_grow rebuilds the group at the wider tier
                # (a short deliberate bubble instead of a burst serving
                # single-lane indefinitely)
                break
            live = self._live_remaining()
            if not live:
                break
            if poison:
                self._maybe_poison()
            k = self.chunk
            tail = self.eng.tail
            if (tail is not None
                    and max(self._effective_remaining()) <= self.chunk - tail):
                # every live lane finishes (or is PREDICTED to steady-
                # exit) inside the chunk, with enough headroom that
                # ceil(rem/tail) tail programs compute strictly fewer
                # masked steps than one full chunk
                k = tail
                self.outer.tail_chunks += 1
            t_disp = wall_clock()
            handle = self.eng.dispatch_chunk(k)
            if self.idle_from is not None:
                self.outer.device_idle_s += t_disp - self.idle_from
                if self.tracer.enabled:
                    # the idle gap, ATTRIBUTED: this exact interval on
                    # this exact group's dispatch row had nothing queued
                    self.tracer.complete("device-idle", self.group_track,
                                         self.idle_from, t_disp, cat="idle")
                self.idle_from = None
            # usage metering: every lane still counting down participates
            # in this chunk (one vectorized add; freed lanes' garbage
            # counts are reset at the next admission)
            self.lane_chunks += self.dev_rem > 0
            np.maximum(self.dev_rem - k, 0, out=self.dev_rem)
            # rollback mode keeps every in-flight boundary restorable:
            # the snapshot is promoted to a lane's last_good only once
            # that boundary's finite bit comes back clean
            snap = self.eng.snapshot_stack() if self.rollback else None
            self.inflight.append(
                (self.seq, handle, self.dev_rem.astype(np.int32), snap,
                 t_disp, k))
            self.seq += 1
            self.outer.chunks_dispatched += 1

    # --- boundary side ----------------------------------------------------
    def _fetch(self, handle) -> np.ndarray:
        """One watchdog-bounded boundary fetch with wall accounting."""
        outer = self.outer
        t0 = wall_clock()
        try:
            return self.eng.fetch_remaining(
                handle, timeout_s=outer.scfg.fetch_timeout_s,
                plan=outer._plan, fetch_index=outer._fetch_seq)
        finally:
            outer._fetch_seq += 1
            t1 = wall_clock()
            outer.boundary_wait_s += t1 - t0
            outer.boundary_waits += 1
            if self.tracer.enabled:
                # boundary_wait_s, attributed: each fetch's blocked wall
                # becomes one span on the scheduler thread's row
                self.tracer.complete("boundary-fetch",
                                     self.tracer.thread_track("scheduler"),
                                     t0, t1, cat="boundary",
                                     args={"bucket": self.track_name})

    def _trace_occupancy(self, lane: int, req: Request, status: str) -> None:
        """Close the lane's occupancy span (admission -> this verdict) on
        its track. Must run BEFORE the finish/fail path pops the record's
        ``_start_t``."""
        tr = self.tracer
        if not tr.enabled:
            return
        t0 = self.outer._by_id[req.id].get("_start_t")
        if t0 is None:
            return
        tr.complete(req.id, self.lane_tracks[lane], t0, cat="lane",
                    trace_id=req.trace_id,
                    args={"status": status, "n": req.cfg.n,
                          "ntime": req.cfg.ntime})
        tr.flow("t", self.lane_tracks[lane], req.trace_id)

    def _judge_lanes(self, seq: int, rem, finite, snap, sync: bool) -> None:
        """Apply one fetched boundary's verdicts to every lane this
        boundary is authoritative for (epoch guard: a chunk dispatched
        before a lane's occupant swap or rollback must not judge the new
        state). Order per lane: health first (a non-finite result must
        never be delivered, even one that 'finished'), then completion,
        then deadline, then last-good promotion."""
        outer = self.outer
        now = wall_clock()
        for lane in range(self.lanes):
            req = self.occupant[lane]
            if req is None or seq < self.epoch[lane]:
                continue
            if finite is not None and not finite[lane]:
                self._handle_nonfinite(lane, req, int(rem[lane]), snap)
            elif rem[lane] == 0 or self.steady_exit[lane] is not None:
                steady_at = self.steady_exit[lane]
                self.steady_exit[lane] = None
                chunks = int(self.lane_chunks[lane])
                steps_done = req.cfg.ntime
                exit_mode = "steps"
                if steady_at is not None:
                    # steady exit retires at the dispatch FRONTIER: the
                    # chunks already in flight keep executing (the
                    # countdown mirror is untouched — the desync check
                    # stays exact) and the retirement snapshot is
                    # enqueued behind them, so the delivered field has
                    # exactly ntime - dev_rem steps — bit-identical to a
                    # fixed-step run truncated there, with zero new
                    # transfers. At depth 0 the frontier IS the
                    # detection boundary. A pipeline that already
                    # dispatched every step simply retires normally.
                    steps_done = req.cfg.ntime - int(self.dev_rem[lane])
                    if steps_done < req.cfg.ntime:
                        exit_mode = "steady"
                        outer.steady_exits += 1
                        # steps_saved_total is also bumped by the
                        # client-thread cache consult (_cache_replay) —
                        # cross-thread now, so every write takes the lock
                        with outer._lock:
                            outer.steps_saved_total += (req.cfg.ntime
                                                        - steps_done)
                        if self.tracer.enabled:
                            self.tracer.instant(
                                "steady-exit", self.lane_tracks[lane],
                                trace_id=req.trace_id,
                                args={"id": req.id, "at_step": steps_done,
                                      "requested": req.cfg.ntime,
                                      "saved": req.cfg.ntime - steps_done,
                                      "predicted_at_step":
                                          req.predicted_steps})
                self._trace_occupancy(lane, req, "retired")
                if sync:
                    outer._finish_sync(self.eng, lane, req, self.writer,
                                       chunks=chunks,
                                       steps_done=steps_done,
                                       exit_mode=exit_mode)
                else:
                    outer._finish_async(self.eng, lane, req, self.writer,
                                        chunks=chunks,
                                        steps_done=steps_done,
                                        exit_mode=exit_mode)
                self.occupant[lane] = None
            elif (cut := outer._deadline_cut(req, now)) is not None:
                done = req.cfg.ntime - int(rem[lane])
                self._trace_occupancy(lane, req, "deadline")
                outer._fail_request(
                    req, "deadline",
                    (f"deadline: cancelled (deadline-preemption) with "
                     f"~{done} of {req.cfg.ntime} steps done; lane "
                     f"{lane} preempted at the chunk boundary"
                     if cut == "cancelled" else
                     f"deadline: exceeded its "
                     f"{1e3 * (req.deadline_t - req.submit_t):.0f} ms "
                     f"budget with ~{done} of {req.cfg.ntime} steps done; "
                     f"lane {lane} preempted at the chunk boundary"),
                    lane=lane,
                    steps_done=done, chunks=int(self.lane_chunks[lane]))
                outer.deadline_misses += 1
                # the lane keeps counting down on device (masked garbage
                # until refilled) so the host mirror stays exact; a
                # refill overwrites buffer + countdown wholesale
                self.occupant[lane] = None
            elif self.rollback and snap is not None:
                self.last_good[lane] = (snap, int(rem[lane]))

    def _handle_nonfinite(self, lane: int, req: Request, rem_at: int,
                          snap) -> None:
        """One lane's finite bit dropped: restore-and-re-step it alone
        (rollback mode, budget permitting) or quarantine the request.
        Either way every other lane is untouched."""
        outer = self.outer
        done = req.cfg.ntime - rem_at
        if self.rollback and self.rb_left[lane] > 0:
            self.rb_left[lane] -= 1
            outer.rollbacks += 1
            if self.tracer.enabled:
                self.tracer.instant("rollback", self.lane_tracks[lane],
                                    trace_id=req.trace_id,
                                    args={"id": req.id, "at_step": done})
            if self.last_good[lane] is not None:
                good_snap, steps_left = self.last_good[lane]
                master_print(
                    f"serve on-nan rollback: request {req.id} (lane {lane}) "
                    f"non-finite at ~step {done}; restoring the last "
                    f"verified boundary ({steps_left} steps left, attempt "
                    f"{_MAX_LANE_ROLLBACKS - self.rb_left[lane]}/"
                    f"{_MAX_LANE_ROLLBACKS})")
                self.eng.restore_lane(lane, good_snap[lane],
                                      float(req.cfg.r), req.cfg.n,
                                      steps_left)
                self.dev_rem[lane] = steps_left
            else:
                # no verified boundary yet: re-admit from the (determin-
                # istic) initial condition — the first-chunk transient
                master_print(
                    f"serve on-nan rollback: request {req.id} (lane {lane}) "
                    f"non-finite at ~step {done}; re-stepping from the "
                    f"initial condition (attempt "
                    f"{_MAX_LANE_ROLLBACKS - self.rb_left[lane]}/"
                    f"{_MAX_LANE_ROLLBACKS})")
                T0 = initial_condition(req.cfg)
                self.eng.load_lane(lane, T0, float(req.cfg.r),
                                   req.cfg.ntime, req.cfg.bc_value)
                self.dev_rem[lane] = req.cfg.ntime
            # boundaries already in flight show the pre-restore (still
            # poisoned) lane: the epoch bump makes them non-authoritative
            self.epoch[lane] = self.seq
            self.last_good[lane] = None
        else:
            exhausted = self.rollback and self.rb_left[lane] == 0
            tried = (f" after {_MAX_LANE_ROLLBACKS} rollbacks "
                     f"(deterministic blow-up)" if exhausted else "")
            if self.tracer.enabled:
                self.tracer.instant("quarantine", self.lane_tracks[lane],
                                    trace_id=req.trace_id,
                                    args={"id": req.id, "at_step": done})
            self._trace_occupancy(lane, req, "nonfinite")
            outer._fail_request(
                req, "nonfinite",
                f"nonfinite: non-finite field detected at ~step {done} of "
                f"{req.cfg.ntime} (lane {lane}){tried} — check the CFL "
                f"bound sigma <= 1/(2*ndim) for this request", lane=lane,
                steps_done=done, chunks=int(self.lane_chunks[lane]))
            outer.lanes_quarantined += 1
            if exhausted:
                # flight-recorder trigger: a lane quarantined after its
                # rollback budget is the postmortem case the ring exists
                # for — the dump holds the whole restore/re-flag history
                outer._flight_dump("quarantine after "
                                   f"{_MAX_LANE_ROLLBACKS} rollbacks "
                                   f"(request {req.id})")
            # free the lane; its NaN field idles masked (and its device
            # countdown keeps draining, mirrored by dev_rem) until a new
            # request's load overwrites the whole lane buffer
            self.occupant[lane] = None
            self.nan_pending[lane] = []
            self.last_good[lane] = None

    def _ingest_numerics(self, seq: int, b: np.ndarray) -> None:
        """Feed one fetched boundary's fused stats rows (rows 2-5 of the
        widened vector — engine.unpack_boundary) to the numerics
        observatory and apply its verdicts. Runs BEFORE ``_judge_lanes``
        under the same epoch guard, so a quarantine verdict frees the
        lane before the health/completion pass sees it — and a stale
        chunk can never judge a swapped-in occupant's physics."""
        outer = self.outer
        stats = unpack_boundary(b)
        rem = b[0]
        tr = self.tracer
        for lane in range(self.lanes):
            req = self.occupant[lane]
            if req is None or seq < self.epoch[lane]:
                continue
            resid = float(stats[0, lane])
            heat = float(stats[3, lane])
            if tr.enabled:
                # Perfetto counter track: the lane's residual/heat as
                # 'C' series — the convergence sparkline on the timeline
                tr.counter(f"numerics lane {lane}", self.group_track,
                           {"resid": resid, "heat": heat})
            events = outer.numerics.observe(
                req.id, resid, float(stats[1, lane]),
                float(stats[2, lane]), heat, int(rem[lane]))
            for ev in events:
                outer._note_numerics_event(self, lane, req,
                                           int(rem[lane]), ev)

    def _quarantine_numerics(self, lane: int, req: Request, rem_at: int,
                             why: str) -> None:
        """``--numerics-guard quarantine``: a violated lane takes the
        PR-5 quarantine exit — structured nonfinite failure, lane freed,
        co-scheduled lanes byte-identical to a clean run (the masking
        contract confines the damage to the lane's own buffer)."""
        outer = self.outer
        done = req.cfg.ntime - rem_at
        if self.tracer.enabled:
            self.tracer.instant("quarantine", self.lane_tracks[lane],
                                trace_id=req.trace_id,
                                args={"id": req.id, "at_step": done,
                                      "why": why})
        self._trace_occupancy(lane, req, "nonfinite")
        outer._fail_request(
            req, "nonfinite",
            f"numerics: {why} violation at ~step {done} of "
            f"{req.cfg.ntime} (lane {lane}) — the field is finite but "
            f"un-physical; check r against the CFL bound "
            f"sigma <= 1/(2*ndim), dtype drift, or an injected perturb "
            f"fault (TROUBLESHOOTING.md)", lane=lane,
            steps_done=done, chunks=int(self.lane_chunks[lane]))
        outer.lanes_quarantined += 1
        self.occupant[lane] = None
        self.nan_pending[lane] = []
        self.perturb_pending[lane] = []
        self.last_good[lane] = None

    def process_boundary(self) -> None:
        """Take one chunk boundary: fetch the OLDEST in-flight boundary
        vector (the newer chunks keep computing behind the transfer),
        judge every lane's health/completion/deadline, refill from the
        queue."""
        if self.inflight:
            seq, handle, predicted, snap, t_disp, k = self.inflight.popleft()
            b = self._fetch(handle)
            t_done = wall_clock()
            rem, finite = b[0], b[1]
            if self.tracer.enabled:
                # chunk-in-flight span: dispatch enqueue -> boundary
                # fetched (under dispatch-ahead the newer chunks compute
                # behind this interval — visibly, on the timeline)
                self.tracer.complete(f"chunk {seq} ({k} steps)",
                                     self.group_track, t_disp, t_done,
                                     cat="chunk",
                                     args={"seq": seq, "k": k})
            outer = self.outer
            if outer.prof.enabled:
                # cost-model feed: boundary service time from timestamps
                # already taken — exact when fenced, per-chunk under a
                # saturated pipeline (prof.CostModel); then the cadenced
                # memory watermark sample, also off the dispatch path
                base = (t_disp if self.last_fetch_t is None
                        else max(self.last_fetch_t, t_disp))
                outer.prof.observe_chunk(self.cost_label, self.lanes,
                                         self.depth, k, t_done - base,
                                         kernel=self.kernel)
                self.last_fetch_t = t_done
                warn = outer.prof.maybe_sample_memory(t_done)
                if warn is not None:
                    outer._mem_warn(warn)
            if not self.inflight:
                self.idle_from = t_done
            if not np.array_equal(rem, predicted):
                raise RuntimeError(
                    f"serve dispatch-ahead desync for bucket {self.key}: "
                    f"device remaining {rem.tolist()} != host-predicted "
                    f"{predicted.tolist()} at chunk {seq} — the lane "
                    f"masking contract broke; results cannot be trusted")
            if outer.numerics is not None:
                self._ingest_numerics(seq, b)
            self._judge_lanes(seq, rem, finite, snap, sync=False)
            outer._note_boundary()
        else:
            # nothing in flight and nothing left to step: occupants whose
            # countdown is already settled at zero (ntime=0 admits, or
            # the final boundary was already inspected) retire directly
            self._judge_lanes(self.seq, self.dev_rem, None, None,
                              sync=False)
        self._fill()

    def has_work(self) -> bool:
        return (bool(self.inflight) or bool(self.q)
                or any(o is not None for o in self.occupant))

    # --- online lane-tier growth ------------------------------------------
    def _growth_wanted(self) -> bool:
        if self.lanes >= self.outer.scfg.lanes:
            return False
        occupied = sum(o is not None for o in self.occupant)
        want = lane_tier(max(1, min(occupied + len(self.q),
                                    self.outer.scfg.lanes)),
                         self.outer.scfg.lanes)
        return want > self.lanes

    def maybe_grow(self) -> None:
        """Streaming admission can outgrow the lane tier this runner was
        born with (the first online request builds a tier-1 group; a
        burst then queues behind one lane). At an empty-pipeline boundary
        — no chunk in flight, so the live stack IS the last judged state
        — rebuild the group at the demanded tier and transplant every
        occupant bit-exactly: crop its field out (one D2H), reload it
        into the wider stack with the same remaining count (the host
        countdown mirror is exact by construction). Bounded cost: tiers
        are powers of two capped at ``--lanes``, so a group grows at most
        log2(lanes) times for its whole lifetime. Offline ``run()`` sizes
        runners from the full queue up front, so this never fires there
        (the PR-3..5 admission traces stay byte-identical)."""
        outer = self.outer
        if self.inflight or not self.allow_growth or not self._growth_wanted():
            return
        occupied = sum(o is not None for o in self.occupant)
        want = lane_tier(max(1, min(occupied + len(self.q),
                                    outer.scfg.lanes)), outer.scfg.lanes)
        old_eng, old_occ = self.eng, self.occupant
        old_rem, old_nan, old_rb = self.dev_rem, self.nan_pending, self.rb_left
        old_chunks, old_pert = self.lane_chunks, self.perturb_pending
        old_steady = self.steady_exit
        if self.tracer.enabled:
            self.tracer.instant("lane-tier-grow", self.group_track,
                                args={"from": self.lanes, "to": want})
        self.lanes = want
        self.eng = LaneEngine(self.key, want, self.chunk,
                              compiled_cache=outer._compiled,
                              on_compile=outer._note_compile,
                              kernel=self.kernel,
                              donate=not self.rollback)
        if self._kernel_fb is not None:
            # the fallback contract is per (bucket, tier): the grown tier
            # is a new compiled program that also fell back
            outer._note_lane_fallback(self.key, want, self._kernel_fb)
        self.occupant = [None] * want
        self.epoch = [self.seq] * want
        self.dev_rem = np.zeros(want, dtype=np.int64)
        self.lane_chunks = np.zeros(want, dtype=np.int64)
        self.nan_pending = [[] for _ in range(want)]
        self.perturb_pending = [[] for _ in range(want)]
        self.rb_left = [0] * want
        self.last_good = [None] * want
        self.steady_exit = [None] * want
        self.lane_tracks = [self.tracer.track(self.track_name, f"lane {i}")
                            for i in range(want)]
        for lane, req in enumerate(old_occ):
            if req is None:
                continue
            T = old_eng.extract_lane(lane, req.cfg.n)
            self.eng.load_lane(lane, T, float(req.cfg.r),
                               int(old_rem[lane]), req.cfg.bc_value)
            self.occupant[lane] = req
            self.dev_rem[lane] = old_rem[lane]
            self.lane_chunks[lane] = old_chunks[lane]
            self.nan_pending[lane] = old_nan[lane]
            self.perturb_pending[lane] = old_pert[lane]
            self.rb_left[lane] = old_rb[lane]
            self.steady_exit[lane] = old_steady[lane]
            # the old tier's stack snapshots have the old lane count: drop
            # them; a post-growth rollback re-steps from the IC instead
        outer.lane_grows += 1
        self._fill()

    # --- synchronous fallback (--dispatch-depth off) ----------------------
    def sync_round(self) -> None:
        """One fenced boundary of the PR-3 shape: dispatch a chunk, fetch
        it immediately (the fetch fences the chunk), judge every lane on
        the scheduler thread, refill. ``run_sync`` loops it to drain; the
        online loop calls it round-robin across groups so depth-0 engines
        still stream admissions."""
        outer = self.outer
        finite = None
        snap = None
        if self._live_remaining():
            if outer._has_lane_faults:
                self._maybe_poison()
            t0 = wall_clock()
            if self.idle_from is not None:
                # device sat idle from the last fetch's return until
                # this dispatch — the fence cost the A/B demonstrates
                outer.device_idle_s += t0 - self.idle_from
                if self.tracer.enabled:
                    self.tracer.complete("device-idle", self.group_track,
                                         self.idle_from, t0, cat="idle")
            b = self._fetch(self.eng.dispatch_chunk())
            rem, finite = b[0], b[1]
            outer.chunks_dispatched += 1
            self.idle_from = wall_clock()
            if self.tracer.enabled:
                self.tracer.complete(f"chunk {self.seq} ({self.chunk} "
                                     f"steps, fenced)", self.group_track,
                                     t0, self.idle_from, cat="chunk",
                                     args={"seq": self.seq,
                                           "k": self.chunk})
            if outer.prof.enabled:
                # fenced boundary: the dispatch->fetch wall IS the chunk
                # service time (cost-model key depth 0, the sync shape)
                outer.prof.observe_chunk(self.cost_label, self.lanes, 0,
                                         self.chunk, self.idle_from - t0,
                                         kernel=self.kernel)
                warn = outer.prof.maybe_sample_memory(self.idle_from)
                if warn is not None:
                    outer._mem_warn(warn)
            self.lane_chunks += self.dev_rem > 0
            np.maximum(self.dev_rem - self.chunk, 0, out=self.dev_rem)
            if self.rollback:
                snap = self.eng.snapshot_stack()
            if outer.numerics is not None:
                self._ingest_numerics(self.seq, b)
            outer._note_boundary()
        else:
            rem = self.dev_rem
        self._judge_lanes(self.seq, rem, finite, snap, sync=True)
        self.seq += 1
        self._fill()

    def run_sync(self) -> None:
        """The PR-3 shape, kept for debugging A/Bs: fetch every boundary
        as its chunk is dispatched (the fetch fences the whole chunk) and
        extract finished lanes on the scheduler thread. No pipelining, no
        tail programs — but the same per-lane fault domains: the boundary
        vector carries the finite bits either way, and here the live
        stack IS the fetched boundary's state, so rollback snapshots are
        taken after the fetch, from a boundary already judged."""
        while self.has_work():
            self.sync_round()
            # every fenced round is an empty-pipeline cut: take an armed
            # engine checkpoint here (depth > 0 ticks in the drive loops)
            self.outer._ckpt_tick()


class MegaLaneRunner:
    """Dispatch-ahead serving for ONE mesh-spanning mega-lane slot.

    The second placement tier (ISSUE 10): a ``_GroupRunner`` peer whose
    "bucket group" is the whole device mesh and whose lane count is one.
    Requests that overflow every bucket queue here (``Engine.submit``)
    and run as the sharded padded-carry chunked advance
    (``serve/engine.py MegaLaneEngine`` over ``backends/sharded.py``),
    wrapped in the exact contract the packed runners live by: a device
    boundary handle per chunk, ``--dispatch-depth`` chunks in flight, a
    host countdown mirror cross-checked against every fetch, the
    owned-cells ``isfinite`` bit riding the boundary D2H, and the
    deadline / quarantine / rollback / watchdog semantics of a fault
    domain whose blast radius is one mesh. ``Engine.run``'s round-robin
    treats it as just another group, so a mega-lane's boundary
    bookkeeping hides under packed-lane compute and vice versa — and,
    per the roofline note (PAPERS.md), the mega chunk's halo-exchange
    boundaries are exactly the slack packed-lane chunk dispatches fill.

    One slot serves one request at a time; ``--mega-lanes N`` slots
    share the engine-wide mega queue (admission order is the engine's
    policy, same as the packed tier). The mesh being a shared physical
    resource, a wedged mega fetch (watchdog) fails the whole mega tier's
    in-flight and queued requests — one mesh, one fault domain."""

    def __init__(self, outer: "Engine", slot: int, q,
                 writer: "async_io.SnapshotWriter"):
        self.outer = outer
        self.slot = slot
        self.q = q
        self.writer = writer
        scfg = outer.scfg
        self.chunk = scfg.chunk
        self.depth = max(1, scfg.dispatch_depth)
        self.rollback = scfg.on_nan == "rollback"
        self.lanes = 1
        self.kernel = "sharded"
        self.key = ("mega", slot)
        # single-lane mirrors of the group runner's per-lane state, so
        # Engine._fail_group (and the round-robin) treat both alike
        self.occupant: List[Optional[Request]] = [None]
        self.epoch = [0]
        self.dev_rem = np.zeros(1, dtype=np.int64)
        self.lane_chunks = np.zeros(1, dtype=np.int64)
        self.nan_pending: List[List[int]] = [[]]
        self.perturb_pending: List[List[tuple]] = [[]]
        self.rb_left = [0]
        self.last_good: List[Optional[tuple]] = [None]
        self.steady_exit: List[Optional[int]] = [None]
        self.seq = 0
        self.inflight: collections.deque = collections.deque()
        self.idle_from: Optional[float] = None
        self.allow_growth = False      # a mega-lane has no tier to grow:
                                       # it already spans the mesh
        self.eng = None                # MegaLaneEngine, per occupant
        self.cost_label = "mega"       # refined per occupant
        self.last_fetch_t: Optional[float] = None
        self.tracer = outer.tracer
        self.track_name = f"mega lane {slot}"
        self.group_track = self.tracer.track(self.track_name, "dispatch")
        self.lane_tracks = [self.tracer.track(self.track_name, "mesh")]
        self._fill()

    # --- admission --------------------------------------------------------
    def _fill(self) -> None:
        """Admit the next queued mega request into this slot: build the
        mesh-spanning engine (seed + AOT chunk compiles, warm via the
        engine-shared cache) on the scheduler thread. Queued requests
        past their deadline are shed here, and an engine-construction
        failure (a compile error on THIS config) fails that one request
        — never the scheduler loop."""
        outer = self.outer
        if outer._ckpt_pause:
            # checkpoint bubble: same no-new-admissions contract as the
            # packed tier — queued mega requests ride the manifest
            return
        while self.occupant[0] is None and self.q:
            with outer._lock:
                req = self.q.pop()
                if req is None:
                    break
                outer._queued_by_tenant[req.tenant] -= 1
                outer.admission_trace.append(req.id)
            now = wall_clock()
            tr = self.tracer
            if tr.enabled:
                policy_mod.note_pop(tr, outer.scfg.policy, req, now)
            cut = outer._deadline_cut(req, now)
            if cut is not None:
                if tr.enabled:
                    tr.instant("deadline-shed", self.group_track,
                               trace_id=req.trace_id,
                               args={"id": req.id}, ts=now)
                outer._fail_request(
                    req, "deadline",
                    "deadline: cancelled (deadline-preemption) while "
                    "still queued (never admitted)"
                    if cut == "cancelled" else
                    f"deadline: exceeded its "
                    f"{1e3 * (req.deadline_t - req.submit_t):.0f} ms "
                    f"budget while still queued (never admitted)")
                outer.deadline_misses += 1
                continue
            if tr.enabled:
                tr.flow("t", self.lane_tracks[0], req.trace_id, ts=now)
            rec = outer._by_id[req.id]
            with outer._lock:
                rec["lane"] = 0
                rec["queue_wait_s"] = round(now - req.submit_t, 6)
                rec["status"] = "running"
                rec["_start_t"] = now
            try:
                mesh = outer._mega_mesh(req.cfg.ndim)
                self.eng = MegaLaneEngine(
                    req.cfg, mesh, self.chunk,
                    compiled_cache=outer._compiled,
                    on_compile=outer._note_mega_compile)
            except Exception as e:  # noqa: BLE001 — per-request record
                outer._fail_request(
                    req, "error",
                    f"mega-lane build failed: {type(e).__name__}: {e}",
                    lane=0)
                continue
            self.cost_label = (f"{req.cfg.ndim}d/n{req.cfg.n}/"
                               f"{req.cfg.dtype}/{req.cfg.bc}")
            rst = req.restore
            if rst:
                # engine-state resume: overwrite the freshly seeded mesh
                # state with the checkpointed owned field (crop -> seed
                # round trip at a chunk boundary is bit-exact — the
                # owned-cell invariance argument of serve/engine.py)
                req.restore = None
                self.eng.load(rst["T"], int(rst["remaining"]))
                self.dev_rem[0] = int(rst["remaining"])
                self.lane_chunks[0] = int(rst.get("chunks", 0))
            else:
                self.dev_rem[0] = req.cfg.ntime
                self.lane_chunks[0] = 0
            self.occupant[0] = req
            self.epoch[0] = self.seq
            self.nan_pending[0] = outer._lane_nan_steps(req)
            self.perturb_pending[0] = outer._lane_perturb_events(req)
            if self.nan_pending[0] or self.perturb_pending[0]:
                outer._has_lane_faults = True
            self.rb_left[0] = _MAX_LANE_ROLLBACKS
            self.last_good[0] = None
            self.steady_exit[0] = None
            if outer.numerics is not None:
                lo, hi = ic_envelope(req.cfg)
                outer.numerics.admit(
                    req.id, lo, hi, req.cfg.dtype, steady_tol=req.tol,
                    log_rate=conv_mod.closed_form_log_rate(req.cfg))
                if rst and rst.get("numerics"):
                    outer.numerics.reseed(req.id, rst["numerics"])

    def maybe_grow(self) -> None:
        """Interface parity with ``_GroupRunner``: nothing to grow."""

    def has_work(self) -> bool:
        return (bool(self.inflight) or bool(self.q)
                or self.occupant[0] is not None)

    # --- dispatch side ----------------------------------------------------
    def _maybe_poison(self) -> None:
        req = self.occupant[0]
        if req is None or not (self.nan_pending[0]
                               or self.perturb_pending[0]):
            return
        done = req.cfg.ntime - int(self.dev_rem[0])
        while self.nan_pending[0] and done >= self.nan_pending[0][0]:
            self.nan_pending[0].pop(0)
            self.eng.poison_center()
        while (self.perturb_pending[0]
               and done >= self.perturb_pending[0][0][0]):
            _, eps = self.perturb_pending[0].pop(0)
            self.eng.perturb_center(eps)

    def dispatch_fill(self) -> None:
        """Queue mesh chunk programs until ``dispatch_depth`` are in
        flight or the occupant has no steps left. The chunk size shrinks
        to the exact remaining count on the final dispatch (the sharded
        advance has no per-step countdown mask — the host picks k, and
        the at-most-one remainder program was AOT-compiled at
        admission)."""
        outer = self.outer
        if outer._ckpt_pause:
            # checkpoint bubble: drain toward the empty cut
            return
        poison = outer._has_lane_faults
        while len(self.inflight) < self.depth:
            rem = int(self.dev_rem[0])
            if self.occupant[0] is None or rem <= 0:
                break
            if poison:
                self._maybe_poison()
            k = min(self.chunk, rem)
            t_disp = wall_clock()
            handle = self.eng.dispatch_chunk(k)
            if self.idle_from is not None:
                outer.device_idle_s += t_disp - self.idle_from
                if self.tracer.enabled:
                    self.tracer.complete("device-idle", self.group_track,
                                         self.idle_from, t_disp, cat="idle")
                self.idle_from = None
            self.lane_chunks[0] += 1
            self.dev_rem[0] = rem - k
            snap = self.eng.snapshot_state() if self.rollback else None
            self.inflight.append(
                (self.seq, handle, self.dev_rem.astype(np.int32).copy(),
                 snap, t_disp, k))
            self.seq += 1
            outer.chunks_dispatched += 1

    # --- boundary side ----------------------------------------------------
    def _fetch(self, handle) -> np.ndarray:
        outer = self.outer
        t0 = wall_clock()
        try:
            return engine_fetch_boundary(
                handle, timeout_s=outer.scfg.fetch_timeout_s,
                plan=outer._plan, fetch_index=outer._fetch_seq)
        finally:
            outer._fetch_seq += 1
            t1 = wall_clock()
            outer.boundary_wait_s += t1 - t0
            outer.boundary_waits += 1
            if self.tracer.enabled:
                self.tracer.complete("boundary-fetch",
                                     self.tracer.thread_track("scheduler"),
                                     t0, t1, cat="boundary",
                                     args={"bucket": self.track_name})

    def _trace_occupancy(self, lane: int, req: Request, status: str) -> None:
        tr = self.tracer
        if not tr.enabled:
            return
        t0 = self.outer._by_id[req.id].get("_start_t")
        if t0 is None:
            return
        tr.complete(req.id, self.lane_tracks[0], t0, cat="lane",
                    trace_id=req.trace_id,
                    args={"status": status, "n": req.cfg.n,
                          "ntime": req.cfg.ntime, "placement": "mega"})
        tr.flow("t", self.lane_tracks[0], req.trace_id)

    def _judge(self, seq: int, rem, finite, snap, sync: bool) -> None:
        """One boundary's verdict for the single mega-lane: health first
        (a non-finite field must never be delivered), then completion,
        then deadline, then last-good promotion — the ``_judge_lanes``
        order, one lane wide. The epoch guard keeps a chunk dispatched
        before a swap/rollback from judging the new occupant."""
        outer = self.outer
        now = wall_clock()
        req = self.occupant[0]
        if req is None or seq < self.epoch[0]:
            return
        if finite is not None and not finite[0]:
            self._handle_nonfinite(req, int(rem[0]), snap)
        elif rem[0] == 0 or self.steady_exit[0] is not None:
            self._retire(req, sync)
        elif (cut := outer._deadline_cut(req, now)) is not None:
            done = req.cfg.ntime - int(rem[0])
            self._trace_occupancy(0, req, "deadline")
            outer._fail_request(
                req, "deadline",
                (f"deadline: cancelled (deadline-preemption) with ~{done} "
                 f"of {req.cfg.ntime} steps done; mega lane preempted at "
                 f"the chunk boundary"
                 if cut == "cancelled" else
                 f"deadline: exceeded its "
                 f"{1e3 * (req.deadline_t - req.submit_t):.0f} ms budget "
                 f"with ~{done} of {req.cfg.ntime} steps done; mega lane "
                 f"preempted at the chunk boundary"), lane=0,
                steps_done=done, chunks=int(self.lane_chunks[0]))
            outer.deadline_misses += 1
            self._release()
        elif self.rollback and snap is not None:
            self.last_good[0] = (snap, int(rem[0]))

    def _release(self) -> None:
        """Free the slot (and the multi-shard state) after a terminal
        verdict; stale in-flight boundaries are drained by seq/epoch."""
        self.occupant[0] = None
        self.eng = None
        self.dev_rem[0] = 0
        self.nan_pending[0] = []
        self.perturb_pending[0] = []
        self.last_good[0] = None
        self.steady_exit[0] = None
        self.epoch[0] = self.seq

    def _handle_nonfinite(self, req: Request, rem_at: int, snap) -> None:
        """The mega-lane's finite bit dropped: restore-and-re-step the
        whole mesh state (rollback mode, budget permitting) or
        quarantine the request — packed lanes in other groups are
        untouched either way."""
        outer = self.outer
        done = req.cfg.ntime - rem_at
        if self.rollback and self.rb_left[0] > 0:
            self.rb_left[0] -= 1
            outer.rollbacks += 1
            if self.tracer.enabled:
                self.tracer.instant("rollback", self.lane_tracks[0],
                                    trace_id=req.trace_id,
                                    args={"id": req.id, "at_step": done})
            if self.last_good[0] is not None:
                good_snap, steps_left = self.last_good[0]
                master_print(
                    f"serve on-nan rollback: mega request {req.id} "
                    f"non-finite at ~step {done}; restoring the last "
                    f"verified boundary ({steps_left} steps left, attempt "
                    f"{_MAX_LANE_ROLLBACKS - self.rb_left[0]}/"
                    f"{_MAX_LANE_ROLLBACKS})")
                self.eng.restore(good_snap, steps_left)
                self.dev_rem[0] = steps_left
            else:
                master_print(
                    f"serve on-nan rollback: mega request {req.id} "
                    f"non-finite at ~step {done}; re-stepping from the "
                    f"initial condition (attempt "
                    f"{_MAX_LANE_ROLLBACKS - self.rb_left[0]}/"
                    f"{_MAX_LANE_ROLLBACKS})")
                self.eng.reload()
                self.dev_rem[0] = req.cfg.ntime
            self.epoch[0] = self.seq
            self.last_good[0] = None
        else:
            exhausted = self.rollback and self.rb_left[0] == 0
            tried = (f" after {_MAX_LANE_ROLLBACKS} rollbacks "
                     f"(deterministic blow-up)" if exhausted else "")
            if self.tracer.enabled:
                self.tracer.instant("quarantine", self.lane_tracks[0],
                                    trace_id=req.trace_id,
                                    args={"id": req.id, "at_step": done})
            self._trace_occupancy(0, req, "nonfinite")
            outer._fail_request(
                req, "nonfinite",
                f"nonfinite: non-finite field detected at ~step {done} of "
                f"{req.cfg.ntime} (mega lane){tried} — check the CFL "
                f"bound sigma <= 1/(2*ndim) for this request", lane=0,
                steps_done=done, chunks=int(self.lane_chunks[0]))
            outer.lanes_quarantined += 1
            if exhausted:
                outer._flight_dump("quarantine after "
                                   f"{_MAX_LANE_ROLLBACKS} rollbacks "
                                   f"(mega request {req.id})")
            self._release()

    def _ingest_numerics(self, seq: int, b: np.ndarray) -> None:
        """The mega mirror of ``_GroupRunner._ingest_numerics``: one
        lane, mesh-wide stats (the sharded advance's cross-shard
        min/max/sum merge — serve/engine.py mega boundary contract)."""
        outer = self.outer
        req = self.occupant[0]
        if req is None or seq < self.epoch[0]:
            return
        stats = unpack_boundary(b)
        resid, heat = float(stats[0, 0]), float(stats[3, 0])
        if self.tracer.enabled:
            self.tracer.counter("numerics mega", self.group_track,
                                {"resid": resid, "heat": heat})
        events = outer.numerics.observe(
            req.id, resid, float(stats[1, 0]), float(stats[2, 0]),
            heat, int(b[0][0]))
        for ev in events:
            outer._note_numerics_event(self, 0, req, int(b[0][0]), ev)

    def _quarantine_numerics(self, lane: int, req: Request, rem_at: int,
                             why: str) -> None:
        """``--numerics-guard quarantine`` for the mega tier: fail the
        occupant nonfinite and free the slot (packed groups untouched —
        the mesh is this request's whole fault domain)."""
        outer = self.outer
        done = req.cfg.ntime - rem_at
        if self.tracer.enabled:
            self.tracer.instant("quarantine", self.lane_tracks[0],
                                trace_id=req.trace_id,
                                args={"id": req.id, "at_step": done,
                                      "why": why})
        self._trace_occupancy(0, req, "nonfinite")
        outer._fail_request(
            req, "nonfinite",
            f"numerics: {why} violation at ~step {done} of "
            f"{req.cfg.ntime} (mega lane) — the field is finite but "
            f"un-physical; check r against the CFL bound "
            f"sigma <= 1/(2*ndim), dtype drift, or an injected perturb "
            f"fault (TROUBLESHOOTING.md)", lane=0,
            steps_done=done, chunks=int(self.lane_chunks[0]))
        outer.lanes_quarantined += 1
        self._release()

    def _retire(self, req: Request, sync: bool) -> None:
        """Completion: crop the padded state to the owned field (a device
        program, enqueued) and hand the D2H + npz publish to the writer
        thread — the mega mirror of ``_finish_async``/``_finish_sync``.
        The writeback closure holds only the cropped snapshot, so the
        padded mesh state is freed with the slot."""
        outer = self.outer
        steady_at = self.steady_exit[0]
        self.steady_exit[0] = None
        steps_done = req.cfg.ntime
        exit_mode = "steps"
        if steady_at is not None:
            # dispatch-frontier retirement, the _judge_lanes contract:
            # in-flight mega chunks still execute (countdown untouched),
            # final_snapshot() crops the state behind them — exactly
            # ntime - dev_rem steps, zero new programs (the AOT chunk
            # sizes never change) and zero new transfers
            steps_done = req.cfg.ntime - int(self.dev_rem[0])
            if steps_done < req.cfg.ntime:
                exit_mode = "steady"
                outer.steady_exits += 1
                with outer._lock:   # cross-thread with _cache_replay
                    outer.steps_saved_total += req.cfg.ntime - steps_done
                if self.tracer.enabled:
                    self.tracer.instant(
                        "steady-exit", self.lane_tracks[0],
                        trace_id=req.trace_id,
                        args={"id": req.id, "at_step": steps_done,
                              "requested": req.cfg.ntime,
                              "saved": req.cfg.ntime - steps_done,
                              "predicted_at_step": req.predicted_steps})
        self._trace_occupancy(0, req, "retired")
        rec = outer._finish_timing(req, chunks=int(self.lane_chunks[0]),
                                   steps_done=steps_done,
                                   exit_mode=exit_mode)
        snap = self.eng.final_snapshot()
        if sync:
            T = MegaLaneEngine.extract(snap)
            outer._writeback_job(rec, req, self.writer, lambda: T)
        else:
            outer._writeback_job(rec, req, self.writer,
                                 lambda: MegaLaneEngine.extract(snap))
        self._release()

    def process_boundary(self) -> None:
        """Take one boundary: fetch the OLDEST in-flight handle, judge,
        refill — the group runner's shape, with the chunk span on the
        mega lane's own process row carrying the halo-exchange geometry
        (fused-exchange count and ghost width) a timeline reader needs
        to see where the mesh fenced."""
        if self.inflight:
            seq, handle, predicted, snap, t_disp, k = self.inflight.popleft()
            b = self._fetch(handle)
            t_done = wall_clock()
            rem, finite = b[0], b[1]
            if self.tracer.enabled:
                kf = self.eng.kf if self.eng is not None else 0
                self.tracer.complete(
                    f"mega chunk {seq} ({k} steps)", self.group_track,
                    t_disp, t_done, cat="chunk",
                    args={"seq": seq, "k": k, "halo_width": kf,
                          "exchanges": -(-k // kf) if kf else 0})
            outer = self.outer
            if outer.prof.enabled:
                base = (t_disp if self.last_fetch_t is None
                        else max(self.last_fetch_t, t_disp))
                outer.prof.observe_chunk(self.cost_label, 1, self.depth,
                                         k, t_done - base,
                                         kernel=self.kernel,
                                         placement="mega")
                self.last_fetch_t = t_done
                warn = outer.prof.maybe_sample_memory(t_done)
                if warn is not None:
                    outer._mem_warn(warn)
            if not self.inflight:
                self.idle_from = t_done
            if not np.array_equal(rem, predicted):
                raise RuntimeError(
                    f"serve dispatch-ahead desync for mega lane "
                    f"{self.slot}: device remaining {rem.tolist()} != "
                    f"host-predicted {predicted.tolist()} at chunk {seq} "
                    f"— the mega countdown contract broke; results "
                    f"cannot be trusted")
            if outer.numerics is not None:
                self._ingest_numerics(seq, b)
            self._judge(seq, rem, finite, snap, sync=False)
            outer._note_boundary()
        else:
            self._judge(self.seq, self.dev_rem, None, None, sync=False)
        self._fill()

    # --- synchronous fallback (--dispatch-depth off) ----------------------
    def sync_round(self) -> None:
        outer = self.outer
        finite = None
        snap = None
        rem_vec = self.dev_rem
        req = self.occupant[0]
        if req is not None and int(self.dev_rem[0]) > 0:
            if outer._has_lane_faults:
                self._maybe_poison()
            k = min(self.chunk, int(self.dev_rem[0]))
            t0 = wall_clock()
            if self.idle_from is not None:
                outer.device_idle_s += t0 - self.idle_from
                if self.tracer.enabled:
                    self.tracer.complete("device-idle", self.group_track,
                                         self.idle_from, t0, cat="idle")
            b = self._fetch(self.eng.dispatch_chunk(k))
            rem_vec, finite = b[0], b[1]
            outer.chunks_dispatched += 1
            self.idle_from = wall_clock()
            if self.tracer.enabled:
                self.tracer.complete(
                    f"mega chunk {self.seq} ({k} steps, fenced)",
                    self.group_track, t0, self.idle_from, cat="chunk",
                    args={"seq": self.seq, "k": k,
                          "halo_width": self.eng.kf})
            if outer.prof.enabled:
                outer.prof.observe_chunk(self.cost_label, 1, 0, k,
                                         self.idle_from - t0,
                                         kernel=self.kernel,
                                         placement="mega")
                warn = outer.prof.maybe_sample_memory(self.idle_from)
                if warn is not None:
                    outer._mem_warn(warn)
            self.lane_chunks[0] += 1
            self.dev_rem[0] = int(self.dev_rem[0]) - k
            if self.rollback:
                snap = self.eng.snapshot_state()
            if outer.numerics is not None:
                self._ingest_numerics(self.seq, b)
            outer._note_boundary()
        self._judge(self.seq, rem_vec, finite, snap, sync=True)
        self.seq += 1
        self._fill()

    def run_sync(self) -> None:
        while self.has_work():
            self.sync_round()
            self.outer._ckpt_tick()


class Engine:
    """Request-driven batched execution engine (library API).

    >>> eng = Engine(ServeConfig(lanes=4, chunk=8, buckets=(64,)))
    >>> rid = eng.submit(HeatConfig(n=32, ntime=100, dtype="float64"))
    >>> records = eng.results()   # drains the queue, returns all records

    ``submit`` only enqueues; ``run``/``results`` executes every admitted
    request to completion via dispatch-ahead continuous batching and
    returns the records in submit order.
    """

    def __init__(self, scfg: Optional[ServeConfig] = None):
        # default resolved per call (ruff B008: a call in a default is
        # evaluated once at definition — harmless for a frozen dataclass,
        # but the pattern is banned uniformly so the one day it guards a
        # mutable default it actually fires)
        scfg = scfg if scfg is not None else ServeConfig()
        self.scfg = scfg
        # request-scoped tracing + always-on flight recorder
        # (runtime/trace.py): every request mints a trace id at submit,
        # every layer appends spans to this bounded ring, and the ring is
        # dumped on watchdog/quarantine/crash — or exported to
        # ``scfg.trace`` at drain. ``trace_buffer=0`` disables recording
        # (ids are still minted: the record schema never flickers).
        self.tracer = trace_mod.Tracer(capacity=scfg.trace_buffer)
        # performance & cost observatory (runtime/prof.py): chunk-cost
        # model, per-tenant usage ledger, memory watermarks, SLO burn
        # monitor — all fed from timestamps this scheduler already takes.
        # Its locks are its own and are only ever taken AFTER (or
        # without) the engine lock, never before it — the gateway's
        # scrape endpoints can therefore never deadlock the hot path.
        targets = dict(SLO_TARGETS)
        targets.update((c, float(t)) for c, t in scfg.slo_targets)
        self.prof = prof_mod.Observatory(
            enabled=scfg.prof, slo_targets=targets,
            mem_poll_every=scfg.mem_poll_every,
            slo_fast_window_s=scfg.slo_fast_window_s,
            slo_slow_window_s=scfg.slo_slow_window_s,
            slo_burn_threshold=scfg.slo_burn_threshold)
        # numerics observatory (runtime/numerics.py, ISSUE 15): solution-
        # quality detectors fed from the stats rows of every fetched
        # boundary. Same lock contract as prof: its lock is its own and
        # only ever taken AFTER (or without) the engine lock, so gateway
        # scrape threads reading snapshot() cannot deadlock the hot path.
        self.numerics = (numerics_mod.NumericsObservatory(
            steady_tol=scfg.steady_tol) if scfg.numerics else None)
        self._queues: Dict[BucketKey, object] = {}  # policy queues
        # second placement tier (ISSUE 10): the engine-wide mega-lane
        # admission queue (same policy object as the bucket queues) plus
        # per-ndim mesh cache; lazily built so packed-only engines never
        # touch the mesh layer
        self._mega_queue = None
        self._mega_meshes: Dict[int, object] = {}
        self._mega_lanes_resolved: Optional[int] = None
        self.mega_compiles = 0    # mega chunk/seed/crop programs built
        self._records: List[dict] = []
        self._by_id: Dict[str, dict] = {}
        self._seq = 0
        # one engine-wide lock: records are mutated and emitted from both
        # the scheduler thread and the SnapshotWriter thread — JSON lines
        # must not interleave mid-line and record mutation must not race.
        # The same lock guards every policy-queue push/pop (the gateway's
        # HTTP threads submit while the scheduler thread pops) and backs
        # the condition the online loop + wait() callers sleep on.
        # Created through runtime/debug.make_lock so HEAT_TPU_LOCKCHECK=1
        # arms the engine<observatory order watchdog on this exact lock.
        self._lock = debug_mod.make_lock("engine")
        self._cond = threading.Condition(self._lock)
        self._listeners: List[Callable[[dict], None]] = []
        # online mode (serve/gateway.py): a background scheduler thread
        # drains continuously; submit() feeds it while lanes run
        self._thread: Optional[threading.Thread] = None
        self._draining = False
        self.loop_error: Optional[BaseException] = None
        # SLO/admission observability: who is queued (per-tenant depth
        # counters back the --tenant-quota check AND the /metrics queue
        # gauge), which request was admitted when (the policy's observable
        # output — the fifo regression test locks this trace), per-class
        # end-to-end latency + queue-depth-at-submit histograms
        self._queued_by_tenant: collections.Counter = collections.Counter()
        self.admission_trace: List[str] = []
        self.lat_hist: Dict[str, policy_mod.Histogram] = {}
        self.depth_hist = policy_mod.Histogram(policy_mod.DEPTH_BUCKETS)
        self.lane_grows = 0          # online lane-tier growth events
        # one compiled-program cache for the engine's lifetime: repeated
        # runs (a long-lived server draining wave after wave) never pay a
        # second (bucket, lane-tier) compile
        self._compiled: Dict = {}
        self.step_compiles = 0    # steady stepping programs built (the
                                  # criterion: at most one per
                                  # (bucket, lane-tier))
        self.tail_compiles = 0    # tail programs built (at most one per
                                  # (bucket, lane-tier), lazily)
        self.compile_s = 0.0
        # dispatch-ahead observability (summary()/cmd_serve surface these)
        self.chunks_dispatched = 0
        self.tail_chunks = 0
        self.boundary_waits = 0
        self.boundary_wait_s = 0.0   # host wall blocked on boundary fetches
        self.device_idle_s = 0.0     # est. device idle: per-group gaps with
                                     # nothing in flight at a boundary
        self.timing = None           # runtime.timing.Timing of the last run
        # lane-kernel observability (ISSUE 9): how many (bucket, tier)
        # groups wanted Pallas and got the XLA fallback (summary(),
        # /metrics gauge heat_tpu_serve_lane_kernel_fallbacks_total)
        self.lane_kernel_fallbacks = 0
        self._lane_fb_seen: set = set()
        # per-lane fault-domain observability (ISSUE 5)
        self.lanes_quarantined = 0   # requests failed nonfinite
        self.rollbacks = 0           # per-lane restore-and-re-step events
        self.deadline_misses = 0     # requests preempted/shed past deadline
        self._cancel_reqs: set = set()  # deadline-preemption by id
                                     # (cancel(): hedged-dispatch loser
                                     # cancel, POST /v1/cancel) — judged
                                     # at the same chunk-boundary sites
                                     # as deadline expiry
        # semantic scheduling (ISSUE 16): until=steady early retirements
        # and the device steps they did NOT run (the effective-throughput
        # multiplier the steady lab gates; /metrics + usage ledger bill
        # saved work as saved)
        self.steady_exits = 0
        self.steps_saved_total = 0
        self.shed = 0                # submits rejected by --max-queue
        self.watchdog_fired = 0      # boundary-fetch watchdog timeouts
        # zero-downtime serving (ISSUE 17): engine-state checkpointing.
        # The cadence clock counts PROCESSED chunk boundaries across all
        # runners; crossing the interval arms _ckpt_pause (runners stop
        # feeding the pipeline), and the driving loop takes the manifest
        # at the first empty-pipeline cut (_ckpt_tick). All mutated on
        # the scheduler thread under the engine lock; the gateway's
        # /drainz?handoff=1 thread flips _ckpt_pause/_handoff under the
        # same lock, and its scrape threads read _engine_ckpt_gen there.
        self.serve_resumed_total = 0  # requests re-admitted by --resume
        self.boundaries_total = 0     # processed chunk boundaries (the
                                      # checkpoint cadence clock and the
                                      # engine-kill@N fault address)
        self._engine_ckpt_gen = 0     # last PUBLISHED manifest generation
        self._engine_ckpt_next = 0    # next generation to write (0 =
                                      # scan the directory first; resume
                                      # seeds loaded generation + 1)
        self._last_ckpt_boundary = 0  # cadence clock at the last publish
        self._ckpt_pause = False      # armed: drain to the empty cut
        self._handoff = False         # drain-to-checkpoint requested
        self._active_runners = ()     # the driving loop's live runners
        self._active_writer = None    # ... and its SnapshotWriter
                                      # (both thread-confined to the
                                      # scheduler thread that set them)
        # engine-scoped fault plan (scfg.inject / HEAT_TPU_FAULTS); None on
        # every normal run — the hot loop then does no fault work at all
        self._plan = faults.plan_for(scfg)
        # two-level solve cache (ISSUE 19): consulted at submit (the one
        # admission door), fed by the writer thread's result publishes
        # and by chunk-boundary engine-checkpoint snapshots. None when
        # --cache off — every call site skips on one is-not-None test,
        # so the cache-off engine is behavior-identical to pre-cache
        # builds (regression-locked).
        self.solvecache = None
        if scfg.cache:
            from pathlib import Path as _Path

            cache_dir = scfg.cache_dir or (
                str(_Path(scfg.out_dir) / "solve-cache") if scfg.out_dir
                else "solve-cache")
            self.solvecache = solvecache_mod.SolveCache(
                cache_dir, max_bytes=scfg.cache_max_bytes,
                plan=self._plan)
        self._has_lane_faults = False  # flips on when a poisoned request
                                       # is admitted (gates _maybe_poison)
        self._fetch_seq = 0            # boundary-fetch counter (fetch-hang
                                       # @N addressing)
        # the gateway's canary prober (serve/probe.py), attached by
        # cmd_serve before any thread starts; None when not armed —
        # /metrics and /statusz read its stats() through this reference
        self.prober = None
        # race sanitizer (no-op unless HEAT_TPU_RACECHECK): exempt fields
        # the committed guard map sanctions as benign — the idempotent
        # mega-lane memo (allow-marked) and the typed object refs
        debug_mod.instrument_races(
            self, label="Engine",
            exempt=frozenset({"_mega_lanes_resolved", "tracer", "prof",
                              "numerics", "scfg", "prober",
                              "solvecache"}))

    # --- mega-lane placement (ISSUE 10) -----------------------------------
    @property
    def mega_lanes(self) -> int:
        """Resolved concurrent-mega-lane budget: the configured value, or
        the auto default (1 when this host has more than one device, 0
        on single-device hosts where overflow stays a rejection).
        Resolved lazily and once — the first overflow admission, summary
        or /metrics render pins it."""
        if self._mega_lanes_resolved is None:
            # heat-tpu: allow[races] idempotent memo — every thread computes the same deterministic value from immutable config, and the publish is one GIL-atomic store; first-writer-wins needs no lock
            self._mega_lanes_resolved = (
                self.scfg.mega_lanes if self.scfg.mega_lanes is not None
                else (1 if mega_device_count() > 1 else 0))
        return self._mega_lanes_resolved

    def _mega_shape(self, ndim: int) -> tuple:
        """The mesh shape a mega-lane of this rank would span (built
        meshes win; the auto factorization otherwise)."""
        mesh = self._mega_meshes.get(ndim)
        if mesh is not None:
            return tuple(mesh.devices.shape)
        from ..parallel.mesh import auto_mesh_shape

        return auto_mesh_shape(mega_device_count(), ndim)

    def _mega_mesh(self, ndim: int):
        mesh = self._mega_meshes.get(ndim)
        if mesh is None:
            from ..parallel.mesh import build_mesh

            mesh = self._mega_meshes[ndim] = build_mesh(ndim, None)
        return mesh

    def _mega_overflow_reason(self, cfg: HeatConfig):
        """``(reason, hint)`` when a bucket-overflow request can NOT run
        as a mega-lane (the enriched rejection record, with the mesh
        capacity ceiling and — when flipping one knob would serve it —
        a machine-readable hint); ``(None, None)`` when it can."""
        biggest = max(self.scfg.buckets)
        base = (f"bucket-overflow: request side {cfg.n} exceeds the "
                f"biggest bucket {biggest}")
        ndev = mega_device_count()
        if self.mega_lanes <= 0:
            shape = "x".join(map(str, self._mega_shape(cfg.ndim)))
            why = ("auto enables mega-lanes only on multi-device hosts"
                   if ndev <= 1 and self.scfg.mega_lanes is None
                   else "--mega-lanes 0")
            return (base + f"; mega-lane placement is off ({why}) though "
                    f"this host's {ndev}-device {shape} mesh could serve "
                    f"it", "enable --mega-lanes")
        shape = self._mega_shape(cfg.ndim)
        bad = [int(s) for s in shape if cfg.n % int(s)]
        if bad:
            return (base + f"; side {cfg.n} does not divide evenly over "
                    f"the {'x'.join(map(str, shape))} device mesh "
                    f"(mega-lane shard constraint) — resubmit at a side "
                    f"divisible by {max(int(s) for s in shape)}", None)
        return None, None

    def _note_mega_compile(self, k: int, seconds: float) -> None:
        """Compile accounting for the mega tier (chunk programs, and the
        k=0 seed/crop pair), kept out of the packed tier's
        one-per-(bucket, tier) step/tail counters."""
        self.mega_compiles += 1
        self.compile_s += seconds
        if self.tracer.enabled:
            t1 = wall_clock()
            self.tracer.complete(f"mega compile k={k}",
                                 self.tracer.thread_track("compiler"),
                                 t1 - seconds, t1, cat="compile",
                                 args={"k": k,
                                       "seconds": round(seconds, 4)})

    def _note_compile(self, k: int, seconds: float) -> None:
        if k == self.scfg.chunk:
            self.step_compiles += 1
        else:
            self.tail_compiles += 1
        self.compile_s += seconds
        if self.tracer.enabled:
            # compile-observatory span: the lazy tail/tier compile is the
            # one that lands mid-drain — make its wall visible on the
            # timeline, not just in the aggregate counter
            t1 = wall_clock()
            self.tracer.complete(f"compile k={k}",
                                 self.tracer.thread_track("compiler"),
                                 t1 - seconds, t1, cat="compile",
                                 args={"k": k,
                                       "seconds": round(seconds, 4)})

    # --- admission --------------------------------------------------------
    def submit(self, cfg: HeatConfig, request_id: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None,
               slo_class: Optional[str] = None,
               until: Optional[str] = None,
               tol: Optional[float] = None,
               _restore: Optional[dict] = None) -> str:
        """Admit one request; returns its id. Unservable requests become
        status='rejected' records instead of raising (see module doc).
        ``deadline_ms`` (request JSONL field of the same name) bounds the
        request's wall time from submission; it overrides the engine
        default ``ServeConfig.deadline_ms``. ``tenant``/``slo_class``
        (JSONL/HTTP fields ``tenant``/``class``) drive the fair-share and
        EDF admission policies; ``until``/``tol`` pick the completion
        semantics (``until="steady"`` retires the lane once its residual
        EWMA passes ``tol`` — default the engine ``--steady-tol`` — with
        ``ntime`` as the hard cap); malformed values raise (the
        JSONL/HTTP front doors pre-validate them into per-request
        rejections).

        ``_restore`` (serve/resume.py only) re-admits a request recovered
        from an engine-state checkpoint: ``{}`` for one that was still
        queued, or a payload with the checkpointed field/remaining/usage
        partials for one that was mid-solve — the admitting lane fill
        continues it at its last boundary, bit-identically.

        Thread-safe: the gateway's HTTP handler threads call this while
        the online scheduler thread is mid-drain — shared state mutates
        under the engine lock and the scheduler is woken per submit."""
        tenant, slo_class = validate_slo_fields(tenant, slo_class)
        until, tol = validate_until_fields(until, tol)
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        deadline_ms = (deadline_ms if deadline_ms is not None
                       else self.scfg.deadline_ms)
        # predictive layer (runtime/convergence.py): an until=steady
        # request gets a closed-form eigenmode ETA at admission — zero
        # observations needed — feeding EDF predicted-finish ordering,
        # the fair-share work estimate, and the predicted-vs-actual
        # retirement instant on the trace
        predicted = None
        if until == "steady":
            eff_tol = tol if tol is not None else self.scfg.steady_tol
            predicted = conv_mod.predict_admission_steps(cfg, eff_tol)
        shed_reason = None
        with self._lock:
            seq = self._seq
            rid = request_id or f"req-{seq:04d}"
            self._seq += 1
            if rid in self._by_id:
                raise ValueError(f"duplicate request id {rid!r}")
            trace_id = self.tracer.mint_trace_id()
            rec = {"id": rid, "n": cfg.n, "ndim": cfg.ndim,
                   "ntime": cfg.ntime, "dtype": cfg.dtype, "bc": cfg.bc,
                   "tenant": tenant, "class": slo_class, "status": "queued",
                   "placement": None,
                   "bucket": None, "lane": None, "queue_wait_s": None,
                   "solve_s": None, "steps_per_s": None, "error": None,
                   "deadline_ms": deadline_ms, "trace_id": trace_id,
                   "until": until, "steps_done": None, "exit": None,
                   "predicted_steps": predicted, "predicted_wall_s": None,
                   "resumed": _restore is not None, "cached": False,
                   "_submit_t": wall_clock()}
            if _restore is not None:
                # usage partials from the checkpointed incarnation: the
                # terminal stamp folds them in (no double billing — the
                # step count spans both incarnations by construction)
                self.serve_resumed_total += 1
                rec["_resumed_lane_s"] = float(_restore.get("lane_s")
                                               or 0.0)
            self._records.append(rec)
            self._by_id[rid] = rec
        if self.tracer.enabled:
            # flow start: the submitting thread (gateway handler, JSONL
            # loader, library caller) anchors the request's cross-thread
            # arrow; admission/retirement/terminal-record hops follow
            self.tracer.flow("s", self.tracer.thread_track(), trace_id,
                             ts=rec["_submit_t"])
        if cfg.bc == "periodic":
            self._reject(rec, "unsupported-bc: periodic has no padded-lane "
                              "form (wraparound would wrap at the bucket "
                              "edge, not the request edge)")
            return rid
        b = _bucket_for(cfg, self.scfg.buckets)
        key = None
        placement = "packed"
        if b is None:
            # two-tier placement (ISSUE 10): bucket overflow falls
            # through to the mega-lane admission queue — one request
            # spanning the whole device mesh — instead of a rejection,
            # wherever mega-lanes are on and the side shards evenly
            reason, hint = self._mega_overflow_reason(cfg)
            if reason is not None:
                self._reject(rec, reason, hint=hint)
                return rid
            placement = "mega"
        else:
            key = BucketKey(ndim=cfg.ndim, n=b, dtype=cfg.dtype, bc=cfg.bc)
        if predicted is not None and self.prof.enabled:
            rec["predicted_wall_s"] = self._forecast_wall(cfg, b, predicted)
        # solve cache consult (ISSUE 19) — at the admission door, after
        # every rejection gate, before a lane or queue slot is taken.
        # Only fixed-step requests CONSUME the cache (an until=steady
        # request's exit step is not knowable from the key — it only
        # populates, under its actual frontier); keys are physics-only,
        # so tenant/class/deadline/id never split entries. Checkpoint
        # re-admissions (_restore) already carry their own field.
        prefix_restore = None
        if (self.solvecache is not None and _restore is None
                and until == "steps"):
            hit = self.solvecache.lookup(cfg)
            if hit is not None and hit["kind"] == "full":
                if self._cache_replay(rec, cfg, b, placement, hit):
                    return rid
            elif hit is not None:
                prefix_restore = self._cache_prefix(rec, cfg, hit)
        with self._cond:
            queued = (sum(len(q) for q in self._queues.values())
                      + (len(self._mega_queue) if self._mega_queue else 0))
            if self.scfg.max_queue and queued >= self.scfg.max_queue:
                self.shed += 1
                shed_reason = (f"overloaded: admission queue full "
                               f"({queued} queued >= --max-queue "
                               f"{self.scfg.max_queue}); resubmit later")
            elif (self.scfg.tenant_quota
                  and self._queued_by_tenant[tenant]
                  >= self.scfg.tenant_quota):
                self.shed += 1
                shed_reason = (f"overloaded: tenant {tenant!r} holds "
                               f"{self._queued_by_tenant[tenant]} queued "
                               f"request(s) >= its --tenant-quota "
                               f"{self.scfg.tenant_quota}; resubmit later")
            else:
                rec["bucket"] = b
                rec["placement"] = placement
                submit_t = rec["_submit_t"]
                if placement == "mega":
                    q = self._mega_queue
                    if q is None:
                        q = self._mega_queue = policy_mod.make_queue(
                            self.scfg.policy, self.scfg.tenant_weights)
                else:
                    q = self._queues.get(key)
                    if q is None:
                        q = self._queues[key] = policy_mod.make_queue(
                            self.scfg.policy, self.scfg.tenant_weights)
                req = Request(
                    id=rid, cfg=cfg, submit_t=submit_t, key=key,
                    placement=placement,
                    deadline_t=(submit_t + deadline_ms / 1e3
                                if deadline_ms is not None else None),
                    tenant=tenant, slo_class=slo_class, seq=seq,
                    trace_id=trace_id, until=until, tol=tol,
                    predicted_steps=predicted,
                    restore=(_restore if _restore else prefix_restore))
                q.push(req)
                if self.tracer.enabled:
                    policy_mod.note_enqueue(self.tracer, self.scfg.policy,
                                            req)
                self._queued_by_tenant[tenant] += 1
                self.depth_hist.observe(float(queued + 1))
                self._cond.notify_all()   # wake the online scheduler
        if shed_reason is not None:
            self._reject(rec, shed_reason)
        return rid

    def _cache_replay(self, rec: dict, cfg: HeatConfig,
                      bucket: Optional[int], placement: str,
                      hit: dict) -> bool:
        """Full cache hit at the admission door: replay the stored npz
        through the normal record/listener path without ever occupying
        a lane — zero chunk programs dispatch, and an out-dir publish is
        a byte copy of the cached artifact (byte-identical to the
        cold-miss npz by construction). Billed as cached: zero
        lane_s/steps, the whole ``ntime`` credited as steps_saved, so
        the hit reconciles across records/ledger//v1/usage like every
        other terminal stamp. Returns False when the entry vanished
        mid-replay (eviction race) — the caller proceeds as a miss."""
        scfg = self.scfg
        path: Optional[str] = None
        T = None
        try:
            nbytes = int(hit["nbytes"])
            if scfg.out_dir:
                p = self.solvecache.replay(hit["path"], scfg.out_dir,
                                           rec["id"])
                path = str(p)
                nbytes = p.stat().st_size
            if scfg.keep_fields or not scfg.out_dir:
                T, _ = solvecache_mod.SolveCache.load(hit["path"])
        except Exception as e:  # noqa: BLE001 — entry evicted under us
            master_print(f"solve cache: replay of {hit['path']} failed "
                         f"({type(e).__name__}: {e}) — recomputing")
            return False
        now = wall_clock()
        with self._lock:
            rec["bucket"] = bucket
            rec["placement"] = placement
            rec["status"] = "ok"
            rec["cached"] = True
            rec["exit"] = "cached"
            rec["queue_wait_s"] = round(now - rec["_submit_t"], 6)
            rec["solve_s"] = 0.0
            rec["steps_per_s"] = None
            rec["steps_done"] = int(cfg.ntime)
            if path is not None:
                rec["path"] = path
            if T is not None:
                rec["T"] = T
            rec["usage"] = {"lane_s": 0.0, "steps": 0, "chunks": 0,
                            "bytes_written": int(nbytes),
                            "steps_saved": int(cfg.ntime),
                            "cached": True}
            self.steps_saved_total += int(cfg.ntime)
        if self.tracer.enabled:
            self.tracer.instant("cache-hit", self.tracer.thread_track(),
                                trace_id=rec["trace_id"],
                                args={"id": rec["id"],
                                      "step": int(hit["step"])})
        self._emit(rec)
        return True

    def _cache_prefix(self, rec: dict, cfg: HeatConfig,
                      hit: dict) -> Optional[dict]:
        """Prefix hit: seed the admitting lane fill from the cached
        field at ``hit['step']`` so the engine steps only the delta.
        The returned payload is the engine-checkpoint resume shape the
        lane fills already consume (``_fill``/mega ``_fill``);
        ``_cache_prefix_steps`` on the record makes the terminal stamp
        bill only the stepped delta, crediting the prefix as
        steps_saved. Returns None when the entry vanished under us —
        the request just runs from the IC."""
        try:
            T, step = solvecache_mod.SolveCache.load(hit["path"])
        except Exception as e:  # noqa: BLE001 — entry evicted under us
            master_print(f"solve cache: prefix read of {hit['path']} "
                         f"failed ({type(e).__name__}: {e}) — "
                         f"recomputing from the IC")
            return None
        remaining = int(cfg.ntime) - int(step)
        if remaining <= 0:
            return None
        with self._lock:
            rec["_cache_prefix_steps"] = int(step)
        if self.tracer.enabled:
            self.tracer.instant("cache-prefix",
                                self.tracer.thread_track(),
                                trace_id=rec["trace_id"],
                                args={"id": rec["id"],
                                      "step": int(step),
                                      "delta": remaining})
        return {"T": T, "remaining": remaining, "chunks": 0}

    def _forecast_wall(self, cfg: HeatConfig, b: Optional[int],
                       steps: int) -> Optional[float]:
        """Cost-model wall forecast for an ``until=steady`` admission,
        keyed on PREDICTED rather than nominal steps (runtime/prof.py).
        Best effort by design: None until the model has observed this
        geometry, and the lane tier is assumed saturated at ``--lanes``
        (the steady state of a loaded server)."""
        d = self.scfg.dispatch_depth
        depth = max(1, d) if d > 0 else 0
        if b is None:
            est = self.prof.cost.estimate_request_s(
                f"{cfg.ndim}d/n{cfg.n}/{cfg.dtype}/{cfg.bc}", 1, depth,
                steps, kernel="sharded", placement="mega")
            return None if est is None else round(est, 6)
        bucket = f"{cfg.ndim}d/n{b}/{cfg.dtype}/{cfg.bc}"
        for kernel in ("pallas", "xla"):
            est = self.prof.cost.estimate_request_s(
                bucket, self.scfg.lanes, depth, steps, kernel=kernel)
            if est is not None:
                return round(est, 6)
        return None

    def _lane_nan_steps(self, req: Request) -> List[int]:
        """Poison thresholds for one admitted request: the union of its
        own plan's and the engine plan's applicable lane-nan steps (the
        two can be the SAME cached plan object — dedupe by identity so a
        shared spec doesn't double-fire)."""
        plans = {id(p): p for p in (faults.plan_for(req.cfg), self._plan)
                 if p is not None}
        steps: set = set()
        for p in plans.values():
            steps.update(p.lane_nan_steps(req.id))
        return sorted(steps)

    def _lane_perturb_events(self, req: Request) -> List[tuple]:
        """Perturb ``(step, eps)`` events for one admitted request — the
        ``_lane_nan_steps`` contract for the numerics-observatory chaos
        channel (same identity-dedupe of a shared plan object)."""
        plans = {id(p): p for p in (faults.plan_for(req.cfg), self._plan)
                 if p is not None}
        events: set = set()
        for p in plans.values():
            events.update(p.perturb_events(req.id))
        return sorted(events)

    def _note_numerics_event(self, runner, lane: int, req: Request,
                             rem_at: int, ev: dict) -> None:
        """One numerics-observatory verdict (runtime/numerics.py event
        dict) becomes policy here: structured record, trace instant,
        flight dump, and — for violations under ``--numerics-guard
        quarantine`` — the runner's quarantine exit. Called from the
        scheduler thread off the boundary fetch, never while holding the
        engine lock (only the quarantine branch takes it, inside
        ``_fail_request``)."""
        done = req.cfg.ntime - rem_at
        if ev["kind"] == "steady":
            json_record("steady_state", id=req.id, lane=lane,
                        steps_done=done, remaining=rem_at,
                        resid=ev["resid"], resid_ewma=ev["resid_ewma"],
                        steady_tol=ev["steady_tol"],
                        trace_id=req.trace_id)
            if self.tracer.enabled:
                self.tracer.instant("steady-state",
                                    runner.lane_tracks[lane],
                                    trace_id=req.trace_id,
                                    args={"id": req.id, "at_step": done})
            if req.until == "steady":
                # semantic scheduling (ISSUE 16): ACT on the detector —
                # flag the lane for frontier retirement; the judge pass
                # of this same process_boundary call consumes the flag
                # (_ingest_numerics runs first, same epoch guard), and
                # _fill backfills the freed lane immediately after
                runner.steady_exit[lane] = rem_at
            return
        why = ev["why"]
        master_print(
            f"serve numerics: request {req.id} (lane {lane}) violated "
            f"the {why} detector at ~step {done} of {req.cfg.ntime} "
            f"(guard: {self.scfg.numerics_guard}) — see "
            f"TROUBLESHOOTING.md")
        json_record("numerics_violation", id=req.id, lane=lane, why=why,
                    steps_done=done, guard=self.scfg.numerics_guard,
                    tmin=ev.get("tmin"), tmax=ev.get("tmax"),
                    lo=ev.get("lo"), hi=ev.get("hi"), tol=ev.get("tol"),
                    heat=ev.get("heat"), heat_prev=ev.get("heat_prev"),
                    dheat=ev.get("dheat"),
                    dheat_ewma=ev.get("dheat_ewma"),
                    trace_id=req.trace_id)
        if self.tracer.enabled:
            self.tracer.instant("numerics-violation",
                                runner.lane_tracks[lane],
                                trace_id=req.trace_id,
                                args={"id": req.id, "why": why,
                                      "at_step": done})
        # flight-recorder trigger: an un-physical field is exactly the
        # postmortem case — the ring holds the lane's whole chunk/residual
        # history up to the escape
        self._flight_dump(f"numerics violation ({why}) on request "
                          f"{req.id}")
        if self.scfg.numerics_guard == "quarantine":
            runner._quarantine_numerics(lane, req, rem_at, why)

    def _reject(self, rec: dict, reason: str,
                hint: Optional[str] = None) -> None:
        with self._lock:
            rec["status"] = "rejected"
            rec["error"] = reason
            if hint is not None:
                # machine-readable remedy (ISSUE 10: an overflow a mesh
                # could have served names the knob that would serve it)
                rec["hint"] = hint
            rec["usage"] = prof_mod.empty_usage()   # schema-stable stamp
        self._emit(rec)

    def _fail_request(self, req: Request, status: str, reason: str,
                      lane: Optional[int] = None, steps_done: int = 0,
                      chunks: int = 0) -> None:
        """Fail ONE request with a structured status (nonfinite /
        deadline / error) — the per-lane fault-domain exit: the record
        carries the reason, the engine keeps serving everyone else.
        ``steps_done``/``chunks`` are the usage-ledger stamp: work the
        failed request DID consume (a preempted lane still occupied the
        group for its chunks — billing that work is the point of the
        per-tenant ledger)."""
        rec = self._by_id[req.id]
        now = wall_clock()
        with self._lock:
            self._cancel_reqs.discard(req.id)
            start = rec.pop("_start_t", None)
            base = rec.pop("_resumed_lane_s", 0.0)
            if start is not None:
                rec["solve_s"] = round(now - start + base, 6)
            elif base:
                rec["solve_s"] = round(base, 6)
            if rec["queue_wait_s"] is None:
                rec["queue_wait_s"] = round(now - req.submit_t, 6)
            if lane is not None:
                rec["lane"] = lane
            rec["status"] = status
            rec["error"] = reason
            rec["steps_done"] = int(steps_done)
            # a cache-prefix admission never ran its prefix steps: bill
            # only the stepped delta, credit the prefix as saved
            prefix = int(rec.pop("_cache_prefix_steps", 0) or 0)
            rec["usage"] = {"lane_s": rec["solve_s"] or 0.0,
                            "steps": max(0, int(steps_done) - prefix),
                            "chunks": int(chunks),
                            "bytes_written": 0, "steps_saved": prefix,
                            "cached": False}
        if self.numerics is not None:
            self.numerics.forget(req.id)   # terminal: drop detector state
        self._emit(rec)

    def _note_lane_fallback(self, key: BucketKey, lanes: int,
                            reason: str) -> None:
        """One (bucket, tier) wanted the Pallas lane program and got the
        XLA oracle instead: degrade LOUDLY — a human line, a structured
        ``lane_kernel_fallback`` record, the summary counter, and the
        /metrics gauge — but never an error (results are bit-identical
        by the oracle contract; only throughput differs). Deduped per
        (bucket, tier) so warm re-runs of the same group don't spam."""
        bucket = f"{key.ndim}d/n{key.n}/{key.dtype}/{key.bc}"
        with self._lock:
            if (key, lanes) in self._lane_fb_seen:
                return
            self._lane_fb_seen.add((key, lanes))
            self.lane_kernel_fallbacks += 1
        master_print(
            f"serve lane-kernel: bucket {bucket} tier {lanes} fell back "
            f"to the XLA lane program ({reason}); results identical, "
            f"throughput reduced — see TROUBLESHOOTING.md")
        json_record("lane_kernel_fallback", bucket=bucket, lanes=lanes,
                    requested=self.scfg.lane_kernel, reason=reason)
        if self.tracer.enabled:
            self.tracer.instant("lane-kernel-fallback",
                                self.tracer.thread_track("scheduler"),
                                args={"bucket": bucket, "lanes": lanes,
                                      "reason": reason})

    def _mem_warn(self, warn: dict) -> None:
        """The leak sentinel fired (runtime/prof.py MemWatermark): one
        structured ``mem_watermark`` record + a human line. Called from
        the scheduler thread at a chunk boundary — never inside the
        dispatch loop."""
        master_print(
            f"mem watermark: device memory grew monotonically by "
            f"{warn['growth_bytes'] / 2**20:.1f} MiB over the last "
            f"{warn['window_samples']} samples to "
            f"{warn['bytes_in_use'] / 2**20:.1f} MiB "
            f"({warn['source']}) — a rollback-stack or lane-grow leak "
            f"looks exactly like this; see TROUBLESHOOTING.md")
        json_record("mem_watermark", **warn)

    def _fail_group(self, runner: "_GroupRunner", exc: BaseException) -> None:
        """The boundary-fetch watchdog fired for one bucket group: its
        device state is unreadable (a wedged fetch means every newer
        chunk is suspect too), so every in-flight occupant and every
        still-queued request of THIS group fails with a structured
        record — and the other groups keep draining. This is the
        fail-clean alternative to `heat-tpu serve` hanging forever on
        one dead fetch. (The online loop reuses it as the generic
        fail-everything exit when the scheduler loop itself dies — only
        a real watchdog timeout bumps the watchdog counter.)"""
        is_watchdog = isinstance(exc, async_io.BoundedFetchTimeout)
        if is_watchdog:
            self.watchdog_fired += 1
            if self.tracer.enabled:
                self.tracer.instant("watchdog-fired", runner.group_track,
                                    args={"bucket": runner.track_name,
                                          "error": str(exc)})
        master_print(f"serve fetch watchdog: bucket {runner.key} boundary "
                     f"fetch hung ({exc}); failing the group's "
                     f"{sum(o is not None for o in runner.occupant)} "
                     f"in-flight and {len(runner.q)} queued request(s)")
        for lane, req in enumerate(runner.occupant):
            if req is not None:
                runner._trace_occupancy(lane, req, "error")
                self._fail_request(
                    req, "error",
                    f"fetch-watchdog: {exc} — lane {lane}'s group state "
                    f"is unreadable; request failed cleanly", lane=lane,
                    steps_done=max(0, req.cfg.ntime
                                   - int(runner.dev_rem[lane])),
                    chunks=int(runner.lane_chunks[lane]))
                runner.occupant[lane] = None
        while True:
            with self._lock:
                req = runner.q.pop()
                if req is not None:
                    self._queued_by_tenant[req.tenant] -= 1
            if req is None:
                break
            self._fail_request(
                req, "error",
                f"fetch-watchdog: {exc} — request was still queued when "
                f"its bucket group's boundary fetch hung")
        runner.inflight.clear()
        if is_watchdog:
            # flight-recorder trigger: the ring holds the wedged
            # request's whole span chain up to the hang — dump it next to
            # the results so the postmortem starts with a timeline
            self._flight_dump(f"fetch watchdog fired for bucket "
                              f"{runner.key}")

    def _flight_dump(self, reason: str) -> None:
        """Flight-recorder dump (watchdog fire / quarantine-after-
        rollbacks / scheduler crash): atomic write of the event ring to
        ``flight_dir`` (default: ``out_dir``; with neither set the dump
        is SKIPPED — never the cwd, which is how 81 stray trace files
        once landed at a repo root). Must never raise into the failure
        path it is documenting. A successful dump additionally emits a
        structured ``flightrec`` record naming the file — operators find
        the dump from the log stream, not by grepping the filesystem —
        and bumps the ``heat_tpu_flightrec_dumps_total`` counter
        (/metrics)."""
        d = self.scfg.flight_dir or self.scfg.out_dir
        if d is None:
            return
        try:
            path = self.tracer.flight_dump(d, reason)
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            master_print(f"flight recorder: dump failed "
                         f"({type(e).__name__}: {e})")
            return
        if path is not None:
            json_record("flightrec", reason=reason, path=str(path),
                        events=len(self.tracer), dump=self.tracer.dumps,
                        max_dumps=trace_mod.MAX_FLIGHT_DUMPS)

    @staticmethod
    def _public(rec: dict) -> dict:
        """A record as callers see it: no field payload (``T`` can be a
        multi-MiB array — poll it explicitly via results()/records), no
        internal ``_``-prefixed bookkeeping."""
        return {k: v for k, v in rec.items()
                if k != "T" and not k.startswith("_")}

    def _emit(self, rec: dict) -> None:
        """Emit one request record: a JSON line (when enabled), the
        per-class latency histogram observation, a condition broadcast
        for ``wait()`` callers, and every registered listener. Called
        from the scheduler thread (rejections) AND the writer thread
        (finishes); the lock keeps concurrent lines from interleaving
        mid-line and snapshots the record fields consistently. Every
        emission is a terminal transition — records are only ever
        emitted once their status can no longer change."""
        now = wall_clock()
        with self._cond:
            snap = self._public(rec)
            listeners = list(self._listeners)
            submit_t = rec.get("_submit_t")
            if submit_t is not None and snap.get("status") != "rejected":
                cls = snap.get("class", "standard")
                h = self.lat_hist.get(cls)
                if h is None:
                    h = self.lat_hist[cls] = policy_mod.Histogram()
                h.observe(max(0.0, now - submit_t))
            # observatory feed: usage ledger + SLO burn windows consume
            # the terminal snapshot (their own locks — engine->prof lock
            # order only); an slo_alert payload is emitted OUTSIDE this
            # lock, like the listeners
            alert = self.prof.note_terminal(snap, now)
            if self.scfg.emit_records:
                # heat-tpu: allow[lock-discipline] the engine lock IS the
                # serialization point: record lines must not interleave
                json_record("serve_request", **snap)
            self._cond.notify_all()
        if alert is not None:
            master_print(
                f"slo alert: class {alert['class']!r} burning its error "
                f"budget at {alert['fast_burn']:.1f}x (fast) / "
                f"{alert['slow_burn']:.1f}x (slow) the sustainable rate "
                f"(target {alert['target']:g}) — see TROUBLESHOOTING.md")
            json_record("slo_alert", **alert)
        if self.tracer.enabled:
            # flow end: the terminal record left the engine (scheduler
            # thread for rejections/failures, writer thread for finishes)
            xid = snap.get("trace_id")
            if xid:
                self.tracer.flow("f", self.tracer.thread_track(), xid,
                                 ts=now)
        # listeners run OUTSIDE the lock: they may call poll()/summary()
        for fn in listeners:
            try:
                fn(snap)
            except Exception:  # noqa: BLE001 — a broken listener must not
                pass           # fail the request it is being told about

    # --- deadline preemption by id (cancel) --------------------------------
    def cancel(self, request_id: str) -> bool:
        """Deadline-preemption by request id — the hedged-dispatch loser
        cancel (fleet router) and ``POST /v1/cancel``. An unknown or
        already-terminal id answers False; otherwise the id is marked
        and the next chunk-boundary deadline judge preempts it with the
        same status ``deadline`` machinery an expired budget uses (a
        queued request is shed at pop). The lane is freed at its next
        boundary — cancellation is cooperative, never mid-chunk."""
        with self._lock:
            rec = self._by_id.get(request_id)
            if rec is None or rec["status"] in TERMINAL_STATUSES:
                return False
            self._cancel_reqs.add(request_id)
            self._cond.notify_all()
        return True

    def _deadline_cut(self, req: Request, now: float) -> Optional[str]:
        """``"expired" | "cancelled" | None`` — the one deadline verdict
        every chunk-boundary judge asks. The unlocked emptiness test
        keeps the no-cancellations hot path free of lock traffic; the
        membership read is re-taken under the lock."""
        if req.deadline_t is not None and now > req.deadline_t:
            return "expired"
        # benign emptiness peek: the set object is created once in
        # __init__ and only mutated (never rebound) under the engine
        # lock; a stale empty read just defers the cut one boundary,
        # and the locked re-check below is authoritative
        if not self._cancel_reqs:
            return None
        with self._lock:
            if req.id in self._cancel_reqs:
                return "cancelled"
        return None

    # --- incremental consumption (poll / wait / listeners) ----------------
    def poll(self, request_id: str) -> Optional[dict]:
        """Snapshot one request's record right now (``None`` — unknown
        id). Unlike ``results()`` this never blocks and never drains:
        the gateway's ``GET /v1/requests/<id>`` and any library caller
        can watch a request finish while the engine keeps running."""
        with self._lock:
            rec = self._by_id.get(request_id)
            return None if rec is None else self._public(rec)

    def field_of(self, request_id: str) -> Optional[np.ndarray]:
        """The final field of a terminal ``ok`` request, or ``None`` —
        from the in-memory record (``keep_fields`` / no out_dir) or the
        published ``.npz``. The gateway's ``GET /v1/requests/<id>?field=1``
        uses this so the canary prober (serve/probe.py) can verify the
        returned solution through the same front door clients use; the
        npz load runs outside the engine lock."""
        with self._lock:
            rec = self._by_id.get(request_id)
            T = rec.get("T") if rec is not None else None
            path = rec.get("path") if rec is not None else None
        if T is not None:
            return np.asarray(T)
        if path is not None:
            with np.load(path) as z:
                return np.asarray(z["T"])
        return None

    def wait(self, request_id: str, timeout: Optional[float] = None
             ) -> Optional[dict]:
        """Block until a request's record is terminal; returns the record
        snapshot, or ``None`` on timeout. Raises KeyError for an unknown
        id (a typo must not wait forever)."""
        deadline = (wall_clock() + timeout) if timeout is not None else None
        with self._cond:
            while True:
                rec = self._by_id.get(request_id)
                if rec is None:
                    raise KeyError(f"unknown request id {request_id!r}")
                if rec["status"] in TERMINAL_STATUSES:
                    return self._public(rec)
                remaining = (None if deadline is None
                             else deadline - wall_clock())
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining if remaining is not None else 0.5)

    def add_listener(self, fn: Callable[[dict], None]) -> None:
        """Register a results-ready callback: ``fn(record_snapshot)``
        fires once per request at its terminal transition — the moment
        its lane retires (or it is rejected/failed), not at drain. May be
        called from the scheduler or writer thread; keep it quick."""
        with self._lock:
            self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def queue_depths(self) -> Dict[str, int]:
        """Queued (not yet admitted) request count per tenant."""
        with self._lock:
            return {t: n for t, n in self._queued_by_tenant.items() if n}

    def backlog_snapshot(self) -> Dict[str, int]:
        """Queued/running work totals for fleet placement (the
        ``GET /v1/status`` control endpoint): integer step sums a router
        converts to predicted backlog seconds via the cost-model rows it
        already scrapes. Reads records only — never runner state, which
        is scheduler-thread-confined — so any HTTP handler thread may
        call this under the engine lock. ``running_steps_bound`` counts
        each resident request at its full ``ntime`` (an upper bound: the
        device-side remaining count is not mirrored here), which is the
        conservative side for load balancing."""
        queued_req = queued_steps = running_req = running_steps = 0
        with self._lock:
            for rec in self._by_id.values():
                st = rec.get("status")
                if st == "queued":
                    queued_req += 1
                    queued_steps += int(rec.get("ntime") or 0)
                elif st == "running":
                    running_req += 1
                    running_steps += int(rec.get("ntime") or 0)
        return {"queued_requests": queued_req,
                "queued_steps": queued_steps,
                "running_requests": running_req,
                "running_steps_bound": running_steps}

    # --- engine-state checkpointing (ISSUE 17) ----------------------------
    def engine_ckpt_dir(self) -> str:
        """Resolved manifest directory: explicit --engine-ckpt-dir, else
        <out_dir>/engine-ckpt, else ./engine-ckpt."""
        from pathlib import Path

        if self.scfg.engine_ckpt_dir:
            return self.scfg.engine_ckpt_dir
        if self.scfg.out_dir:
            return str(Path(self.scfg.out_dir) / "engine-ckpt")
        return "engine-ckpt"

    def _note_boundary(self) -> None:
        """One processed chunk boundary (every runner calls this from the
        scheduler thread): advance the checkpoint cadence clock, arm the
        checkpoint pause when the interval is crossed, and give
        ``engine-kill@N`` its boundary address."""
        with self._lock:
            self.boundaries_total += 1
            n = self.boundaries_total
            interval = self.scfg.engine_ckpt_interval
            if (interval > 0 and not self._ckpt_pause
                    and n - self._last_ckpt_boundary >= interval):
                self._ckpt_pause = True
        if self._plan is not None:
            self._plan.maybe_engine_kill(n)

    def _ckpt_tick(self) -> None:
        """Take the armed checkpoint once the pipeline is EMPTY: every
        runner's in-flight deque drained, so the live device state is
        exactly the last judged boundary (the ``maybe_grow`` transplant
        precedent — the consistent cut). Called once per scheduler round
        by the driving loops; a no-op unless the pause is armed."""
        if not self._ckpt_pause:
            return
        runners = self._active_runners or ()
        if any(r.inflight for r in runners):
            return
        try:
            self._engine_checkpoint(reason="interval")
        finally:
            with self._cond:
                self._ckpt_pause = False
                self._last_ckpt_boundary = self.boundaries_total
                self._cond.notify_all()

    def _engine_checkpoint(self, reason: str) -> None:
        """Snapshot the whole engine at THIS empty-pipeline cut: one
        on-device copy per occupied lane (D2H deferred to the writer
        thread), plus a JSON manifest of lane occupancy, queued requests
        in policy order, and usage partials. The manifest is submitted to
        the FIFO writer AFTER every field job and every earlier
        writeback, so a manifest on disk proves everything it references
        is durable — a kill mid-generation leaves fields without a
        manifest and discovery falls back one generation."""
        from pathlib import Path

        d = Path(self.engine_ckpt_dir())
        with self._lock:
            if self._engine_ckpt_next <= 0:
                self._engine_ckpt_next = ckpt_mod.next_engine_generation(d)
            gen = self._engine_ckpt_next
            self._engine_ckpt_next = gen + 1
        now = wall_clock()
        inflight_entries: List[dict] = []
        field_jobs: List = []
        failed: List[str] = []

        def _entry(req: Request, remaining: int, chunks: int,
                   lane_s: float, numerics) -> dict:
            rec = self._by_id[req.id]
            return {"id": req.id,
                    "cfg": dataclasses.asdict(req.cfg),
                    "fingerprint": ckpt_mod.config_fingerprint(req.cfg),
                    "placement": req.placement,
                    "remaining": int(remaining),
                    "steps_done": int(req.cfg.ntime - remaining),
                    "chunks": int(chunks),
                    "lane_s": round(float(lane_s), 6),
                    "until": req.until, "tol": req.tol,
                    "tenant": req.tenant, "class": req.slo_class,
                    "deadline_ms": rec.get("deadline_ms"),
                    "seq": req.seq,
                    "numerics": numerics}

        def _field_job(rid: str, fp: str, remaining: int, get_field,
                       cfg: Optional[HeatConfig] = None):
            def job():
                try:
                    T = get_field()
                    ckpt_mod.save_engine_field(d, gen, rid, T, fp,
                                               remaining)
                except BaseException as e:  # noqa: BLE001 — abort the gen
                    failed.append(f"{rid}: {type(e).__name__}: {e}")
                    return
                # chunk-boundary snapshots double as the solve cache's
                # prefix store (ISSUE 19): a later identical-physics
                # request seeds a lane from this cut and steps only the
                # delta. Best effort — put() swallows its own failures.
                if (self.solvecache is not None and cfg is not None
                        and remaining > 0):
                    step = int(cfg.ntime) - int(remaining)
                    if step > 0:
                        self.solvecache.put(cfg, step, T=T,
                                            kind="snapshot")
            job._trace = (f"engine-ckpt field {rid}", None)
            return job

        for r in (self._active_runners or ()):
            mega = isinstance(r, MegaLaneRunner)
            for lane, req in enumerate(r.occupant):
                if req is None:
                    continue
                remaining = int(r.dev_rem[lane])
                rec = self._by_id[req.id]
                lane_s = (now - rec.get("_start_t", now)
                          + rec.get("_resumed_lane_s", 0.0))
                num = (self.numerics.export_state(req.id)
                       if self.numerics is not None else None)
                e = _entry(req, remaining, int(r.lane_chunks[lane]),
                           lane_s, num)
                if mega:
                    snap = r.eng.final_snapshot()
                    get_field = (lambda s=snap:
                                 MegaLaneEngine.extract(s))
                else:
                    snap = r.eng.snapshot_lane(lane)
                    get_field = (lambda eng=r.eng, s=snap, n=req.cfg.n:
                                 eng.extract(s, n))
                inflight_entries.append(e)
                field_jobs.append(_field_job(req.id, e["fingerprint"],
                                             remaining, get_field,
                                             cfg=req.cfg))
        queued_entries: List[dict] = []
        with self._lock:
            queues = list(self._queues.values())
            if self._mega_queue is not None:
                queues.append(self._mega_queue)
            queued_reqs = [q2 for q in queues for q2 in q.items()]
        for req in sorted(queued_reqs, key=lambda q2: q2.seq):
            rst = req.restore
            if rst:
                # a resumed request still waiting for a lane carries its
                # checkpointed mid-solve field in host memory — persist
                # it as an in-flight entry or its progress would be lost
                e = _entry(req, int(rst["remaining"]),
                           int(rst.get("chunks", 0)),
                           float(rst.get("lane_s", 0.0)),
                           rst.get("numerics"))
                inflight_entries.append(e)
                field_jobs.append(_field_job(
                    req.id, e["fingerprint"], int(rst["remaining"]),
                    lambda rst=rst: rst["T"], cfg=req.cfg))
            else:
                e = _entry(req, req.cfg.ntime, 0, 0.0, None)
                e.pop("numerics")
                queued_entries.append(e)
        with self._lock:
            live = ({e["id"] for e in inflight_entries}
                    | {e["id"] for e in queued_entries})
            done = sorted(rid for rid in self._by_id if rid not in live)
        manifest = {"kind": ckpt_mod.ENGINE_MANIFEST_KIND,
                    "version": ckpt_mod.ENGINE_MANIFEST_VERSION,
                    "generation": gen, "reason": reason,
                    "boundaries": self.boundaries_total,
                    "policy": self.scfg.policy,
                    "inflight": inflight_entries,
                    "queued": queued_entries,
                    "done": done}

        def manifest_job():
            if failed:
                master_print(
                    f"engine checkpoint: generation {gen} ABORTED — "
                    f"{len(failed)} lane field(s) failed to persist "
                    f"({'; '.join(failed)}); the previous generation "
                    f"remains the resume point")
                return
            path = ckpt_mod.save_engine_manifest(d, gen, manifest,
                                                 plan=self._plan)
            with self._lock:
                self._engine_ckpt_gen = gen
            json_record("engine_ckpt", generation=gen, reason=reason,
                        path=str(path), boundaries=manifest["boundaries"],
                        inflight=len(inflight_entries),
                        queued=len(queued_entries), done=len(done))
        manifest_job._trace = (f"engine-ckpt manifest gen {gen}", None)

        writer = self._active_writer
        if writer is not None:
            for job in field_jobs:
                writer.submit(job)
            writer.submit(manifest_job)
        else:
            for job in field_jobs:
                job()
            manifest_job()

    # --- execution --------------------------------------------------------
    def run(self) -> List[dict]:
        """Drain every queued request through dispatch-ahead continuous
        batching; returns all records (submit order). Reentrant: new
        submits after a run are served by the next run against warm
        compiled programs."""
        from ..runtime.timing import Timing

        if self.online:
            raise RuntimeError(
                "Engine.run()/results() cannot be called while the online "
                "scheduler thread is serving — use poll()/wait() for "
                "records, shutdown() to drain")
        writer = async_io.SnapshotWriter(tracer=self.tracer)
        t0 = wall_clock()
        try:
            runners = [
                _GroupRunner(self, key, self._queues[key], writer)
                for key in list(self._queues) if self._queues[key]
            ]
            if (self._mega_queue and len(self._mega_queue)
                    and self.mega_lanes > 0):
                # one runner per occupied mega slot: round-robined with
                # the packed groups, so a mega boundary's bookkeeping
                # hides under packed compute and vice versa
                runners += [
                    MegaLaneRunner(self, i, self._mega_queue, writer)
                    for i in range(min(self.mega_lanes,
                                       len(self._mega_queue)))]
            # engine-state checkpointing reads the live runners + writer
            # from the driving loop (scheduler-thread-confined)
            self._active_runners = tuple(runners)
            self._active_writer = writer
            if self.scfg.dispatch_depth == 0:
                # synchronous debugging fallback: groups drain one at a
                # time with a fence at every boundary (the PR-3 shape)
                for r in runners:
                    try:
                        r.run_sync()
                    except async_io.BoundedFetchTimeout as e:
                        self._fail_group(r, e)
            else:
                live = [r for r in runners if r.has_work()]
                while live:
                    # an armed engine checkpoint fires at the empty cut,
                    # BEFORE the pipeline refills (see _ckpt_tick)
                    self._ckpt_tick()
                    # prime every group's device queue before anyone
                    # blocks: one group's boundary D2H + bookkeeping then
                    # hides under the other groups' queued compute
                    for r in live:
                        r.dispatch_fill()
                    nxt = []
                    for r in live:
                        try:
                            r.process_boundary()
                            r.dispatch_fill()  # refilled lanes step while
                                               # other groups take
                                               # boundaries
                        except async_io.BoundedFetchTimeout as e:
                            # the watchdog is a GROUP fault domain: fail
                            # this group's requests, keep draining the rest
                            self._fail_group(r, e)
                            continue
                        if r.has_work():
                            nxt.append(r)
                    live = nxt
        except BaseException as e:
            # flight-recorder trigger: the scheduler loop died — dump the
            # ring first (cheap, bounded), THEN drain: every writeback
            # already queued still lands (or fails per-request) — no
            # orphan *.tmp, no dropped result — but a writer error must
            # not mask the scheduler error already propagating
            self._flight_dump(f"scheduler crashed: {type(e).__name__}: {e}")
            writer.drain(raise_errors=False)
            self._active_runners, self._active_writer = (), None
            raise
        # always-at-drain checkpoint (engine_ckpt_interval > 0 opts in):
        # the batch's end state — every request done — becomes the newest
        # generation, so a later --resume re-admits nothing twice
        if self.scfg.engine_ckpt_interval > 0:
            self._engine_checkpoint(reason="drain")
        # normal exit: per-request jobs swallow their own failures, so a
        # surviving writer error here is a real bug and must surface
        writer.drain()
        self._active_runners, self._active_writer = (), None
        self._stamp_timing(Timing, wall_clock() - t0)
        if self.tracer.enabled:
            self.tracer.complete("engine.run", self.tracer.thread_track(),
                                 t0, cat="engine")
            if self.scfg.trace:
                self.tracer.export(self.scfg.trace)
        return list(self._records)

    def _stamp_timing(self, Timing, wall: float) -> None:
        mem = self.prof.mem.snapshot() if self.scfg.prof else {}
        num = self.numerics
        self.timing = Timing(total_s=wall, solve_s=wall,
                             compile_s=self.compile_s,
                             dispatch_depth=self.scfg.dispatch_depth,
                             serve_policy=self.scfg.policy,
                             boundary_wait_s=round(self.boundary_wait_s, 6),
                             lanes_quarantined=self.lanes_quarantined,
                             rollbacks=self.rollbacks,
                             deadline_misses=self.deadline_misses,
                             shed=self.shed,
                             mem_peak_bytes=mem.get("peak_bytes"),
                             steady_lanes=(num.steady_total
                                           if num is not None else None),
                             numerics_violations=(
                                 num.violation_total
                                 if num is not None else None))

    def results(self) -> List[dict]:
        """``run`` + records (the common library call)."""
        if (any(self._queues.values())
                or (self._mega_queue and len(self._mega_queue))):
            self.run()
        return list(self._records)

    # --- online mode (the gateway's engine shape) -------------------------
    @property
    def online(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    @property
    def draining(self) -> bool:
        return self._draining

    def start(self) -> "Engine":
        """Start the online scheduler thread: from here on ``submit()``
        feeds lanes *while they run* — requests arriving between chunk
        boundaries are admitted at the next one (the Orca iteration-level
        contract, now actually online). Idempotent while running."""
        # background-thread debug plumbing: uncaught crashes in the
        # scheduler/writer/handler threads become structured thread_crash
        # records, and the race sanitizer's record mode can flight-dump
        debug_mod.install_thread_excepthook()
        debug_mod.set_flight_dump_hook(self._flight_dump)
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._draining = False
            self.loop_error = None
            self._thread = threading.Thread(
                target=self._serve_loop, daemon=True,
                name="heat-tpu-serve-scheduler")
            self._thread.start()
        return self

    def begin_drain(self, handoff: bool = False) -> None:
        """Stop admission-by-policy: the online loop finishes every lane
        already admitted AND every request already queued, then exits.
        Callers gate *new* work themselves (the gateway 503s new solves
        the moment draining flips). ``handoff=True`` is drain-to-
        checkpoint (POST /drainz?handoff=1): the loop additionally stops
        lane fills and chunk dispatch, takes the in-flight boundaries
        already queued, checkpoints the whole engine at the first
        empty-pipeline cut — WITHOUT waiting for lanes to finish — and
        exits; ``serve --resume`` picks the work up where it stopped.
        Idempotent, and a later plain drain never cancels a requested
        handoff."""
        with self._cond:
            self._draining = True
            if handoff:
                self._handoff = True
                self._ckpt_pause = True
            self._cond.notify_all()

    def shutdown(self, timeout: Optional[float] = None) -> bool:
        """``begin_drain`` + join the scheduler thread. Returns True once
        the loop has exited (False = still draining after ``timeout``).
        Idempotent: safe to call repeatedly and without ``start()``."""
        self.begin_drain()
        t = self._thread
        if t is None:
            return True
        t.join(timeout)
        if t.is_alive():
            return False
        with self._lock:
            self._thread = None
        return True

    def _serve_loop(self) -> None:
        """The online scheduler: the same dispatch-ahead round-robin as
        ``run()``, but runners persist for the engine's lifetime, new
        bucket groups appear as their first request arrives, idle groups
        grow their lane tier when a burst outruns it, and an empty engine
        parks on the condition variable until a submit (or drain) wakes
        it. Exits when draining AND idle; the writer drains on every
        exit path so no accepted request's writeback is dropped."""
        from ..runtime.timing import Timing

        writer = async_io.SnapshotWriter(tracer=self.tracer)
        # bucket groups keyed by BucketKey; mega slots by ("mega-slot", i)
        runners: Dict[object, object] = {}
        self._active_writer = writer
        t0 = wall_clock()
        try:
            while True:
                if self._handoff:
                    # drain-to-checkpoint: no fills, no new dispatch —
                    # take only the boundaries already in flight, then
                    # checkpoint at the first empty cut and exit. Lane
                    # occupants stay status="running" (no terminal
                    # records); they and the queue ride the manifest.
                    self._active_runners = tuple(runners.values())
                    busy = [r for r in runners.values() if r.inflight]
                    for r in busy:
                        try:
                            r.process_boundary()
                        except async_io.BoundedFetchTimeout as e:
                            self._fail_group(r, e)
                    if not any(r.inflight for r in runners.values()):
                        self._engine_checkpoint(reason="handoff")
                        break
                    continue
                with self._lock:
                    keys = [k for k, q in self._queues.items() if q]
                for key in keys:
                    r = runners.get(key)
                    if r is None:
                        r = runners[key] = _GroupRunner(
                            self, key, self._queues[key], writer)
                        r.allow_growth = True
                    else:
                        r.maybe_grow()
                        r._fill()
                if (self._mega_queue and len(self._mega_queue)
                        and self.mega_lanes > 0):
                    # mega slots appear as their first overflow request
                    # arrives and persist for the engine's lifetime,
                    # like the bucket runners
                    for i in range(self.mega_lanes):
                        mkey = ("mega-slot", i)
                        mr = runners.get(mkey)
                        if mr is None:
                            runners[mkey] = MegaLaneRunner(
                                self, i, self._mega_queue, writer)
                        else:
                            mr._fill()
                self._active_runners = tuple(runners.values())
                self._ckpt_tick()
                live = [r for r in runners.values() if r.has_work()]
                if not live:
                    with self._cond:
                        if (self._draining
                                and not any(
                                    q for q in self._queues.values())
                                and not (self._mega_queue
                                         and len(self._mega_queue))):
                            break
                        # parked: a submit()/begin_drain() notify wakes us;
                        # the timeout only bounds lost-wakeup worst cases
                        self._cond.wait(0.05)
                    continue
                if self.scfg.dispatch_depth == 0:
                    for r in live:
                        try:
                            r.sync_round()
                        except async_io.BoundedFetchTimeout as e:
                            self._fail_group(r, e)
                else:
                    for r in live:
                        r.dispatch_fill()
                    for r in live:
                        try:
                            r.process_boundary()
                            r.dispatch_fill()
                        except async_io.BoundedFetchTimeout as e:
                            self._fail_group(r, e)
            # normal drain exit (the handoff exit checkpointed already,
            # pre-break): an interval-opted engine always leaves a final
            # generation at drain — the zero-downtime restart point
            if self.scfg.engine_ckpt_interval > 0 and not self._handoff:
                self._engine_checkpoint(reason="drain")
        except BaseException as e:  # noqa: BLE001 — surfaced via loop_error
            # a scheduler-loop crash in a daemon thread has nowhere to
            # propagate: record it (gateway /healthz + cmd_serve check it)
            # and fail every in-flight/queued request cleanly
            with self._lock:
                self.loop_error = e
            master_print(f"serve scheduler loop failed: "
                         f"{type(e).__name__}: {e}")
            self._flight_dump(f"scheduler loop crashed: "
                              f"{type(e).__name__}: {e}")
            for r in runners.values():
                self._fail_group(r, e)
        finally:
            try:
                writer.drain(raise_errors=False)
            finally:
                self._active_runners, self._active_writer = (), None
                self._stamp_timing(Timing, wall_clock() - t0)
                if self.tracer.enabled:
                    self.tracer.complete("serve-loop",
                                         self.tracer.thread_track(), t0,
                                         cat="engine")
                    if self.scfg.trace:
                        try:
                            self.tracer.export(self.scfg.trace)
                        except OSError as te:
                            master_print(f"trace export to "
                                         f"{self.scfg.trace} failed: {te}")
                with self._cond:
                    self._cond.notify_all()  # unblock wait() callers

    # --- lane retirement --------------------------------------------------
    def _finish_timing(self, req: Request, chunks: int = 0,
                       steps_done: Optional[int] = None,
                       exit_mode: str = "steps") -> dict:
        steps = int(req.cfg.ntime if steps_done is None else steps_done)
        rec = self._by_id[req.id]
        now = wall_clock()
        with self._lock:
            start = rec.pop("_start_t", now)
            # a resumed request's first incarnation billed lane seconds
            # too — fold the checkpointed partial in; steps_done already
            # spans both incarnations (ntime - final remaining)
            lane_s = (now - start) + rec.pop("_resumed_lane_s", 0.0)
            # a cache-prefix admission (ISSUE 19) seeded the lane at
            # _cache_prefix_steps: the lane only STEPPED the delta —
            # bill that, credit the prefix as steps_saved (riding the
            # same accounting as steady early exits)
            prefix = int(rec.pop("_cache_prefix_steps", 0) or 0)
            stepped = max(0, steps - prefix)
            rec["solve_s"] = round(lane_s, 6)
            rec["steps_per_s"] = (round(stepped / lane_s, 3)
                                  if lane_s > 0 else None)
            rec["steps_done"] = steps
            rec["exit"] = exit_mode
            # the usage-ledger stamp (runtime/prof.py): what THIS request
            # consumed — bytes_written is finalized by the writer thread
            # once the publish lands, before the record is emitted.
            # Semantic scheduling bills ACTUAL steps; the steps a steady
            # exit did not run (or a cache prefix made unnecessary) are
            # credited as steps_saved.
            rec["usage"] = {"lane_s": rec["solve_s"],
                            "steps": stepped,
                            "chunks": int(chunks), "bytes_written": 0,
                            "steps_saved": int(req.cfg.ntime) - stepped,
                            "cached": False}
            if prefix:
                self.steps_saved_total += prefix
        if self.numerics is not None:
            self.numerics.forget(req.id)   # terminal: drop detector state
        return rec

    def _writeback_job(self, rec: dict, req: Request,
                       writer: "async_io.SnapshotWriter",
                       get_field) -> None:
        """Build + submit the writer-thread job for one finished request.
        ``get_field()`` produces the host field — under dispatch-ahead it
        performs the snapshot D2H *in the writer thread*; the sync
        fallback passes a host array already fetched."""
        cfg, scfg = req.cfg, self.scfg
        attempts = {"n": 0}
        # captured before the job runs: _finish_timing already stamped the
        # actual step count (ntime, or the steady-exit frontier)
        steps_done = rec.get("steps_done")

        def job():
            # Runs in the writer thread. Transient sink errors are
            # re-raised so the SnapshotWriter's bounded in-thread retry
            # (backoff, same budget as checkpoints) gets its shot; a final
            # failure is recorded on THIS request and swallowed — it must
            # not poison writer._exc and kill the other lanes' drain.
            attempts["n"] += 1
            try:
                T = get_field()
                plan = faults.plan_for(cfg)
                if plan is not None:
                    plan.sink_fault(cfg.ntime)
                path = (str(_write_result(scfg.out_dir, req.id, T, cfg,
                                          steps=steps_done))
                        if scfg.out_dir else None)
                # bytes the tenant's result cost: the published file's
                # size, or the in-memory field bytes when nothing hits
                # disk — finalized HERE (writer thread) so the ledger add
                # at emission sees the complete stamp
                from pathlib import Path as _Path

                nbytes = (_Path(path).stat().st_size if path is not None
                          else int(T.nbytes))
                with self._lock:
                    if scfg.keep_fields or not scfg.out_dir:
                        rec["T"] = T
                    if path is not None:
                        rec["path"] = path
                    rec["status"] = "ok"
                    rec["usage"]["bytes_written"] = int(nbytes)
                # solve-cache population (ISSUE 19), on the writer
                # thread after the publish landed: a byte copy of the
                # published artifact (or the identical serialization
                # when nothing hit disk), keyed under the ACTUAL step
                # count — a steady early exit caches under its exit
                # frontier so later fixed-step requests can prefix-hit
                # it. Best effort: put() swallows its own failures.
                if self.solvecache is not None:
                    self.solvecache.put(
                        cfg, int(cfg.ntime if steps_done is None
                                 else steps_done),
                        T=T, src_path=path, kind="result")
            except BaseException as e:  # noqa: BLE001 — per-request record
                if async_io.is_transient(e) and attempts["n"] <= writer.retries:
                    raise
                with self._lock:
                    rec["status"] = "error"
                    rec["error"] = f"{type(e).__name__}: {e}"
            self._emit(rec)

        # the writer thread labels its span with the request it serves
        # (snapshot D2H + atomic publish, on the writer's own track)
        job._trace = (f"writeback {req.id}", rec.get("trace_id"))
        writer.submit(job)

    def _finish_async(self, eng: LaneEngine, lane: int, req: Request,
                      writer, chunks: int = 0,
                      steps_done: Optional[int] = None,
                      exit_mode: str = "steps") -> None:
        """Dispatch-ahead retirement: take a one-lane ON-DEVICE snapshot
        (enqueued behind the in-flight chunks; the scheduler thread never
        blocks) and move the D2H + writeback wholly into the writer."""
        rec = self._finish_timing(req, chunks=chunks, steps_done=steps_done,
                                  exit_mode=exit_mode)
        snap = eng.snapshot_lane(lane)
        n = req.cfg.n
        self._writeback_job(rec, req, writer, lambda: eng.extract(snap, n))

    def _finish_sync(self, eng: LaneEngine, lane: int, req: Request,
                     writer, chunks: int = 0,
                     steps_done: Optional[int] = None,
                     exit_mode: str = "steps") -> None:
        """Sync-fallback retirement: fetch the lane on the scheduler
        thread (fences every chunk in flight), write back in the writer."""
        rec = self._finish_timing(req, chunks=chunks, steps_done=steps_done,
                                  exit_mode=exit_mode)
        T = eng.extract_lane(lane, req.cfg.n)
        self._writeback_job(rec, req, writer, lambda: T)

    # --- reporting --------------------------------------------------------
    def summary(self) -> dict:
        with self._lock:
            by_status = collections.Counter(
                r["status"] for r in self._records)
            by_placement = collections.Counter(
                r["placement"] for r in self._records
                if r.get("placement"))
            n = len(self._records)
            queued = (sum(len(q) for q in self._queues.values())
                      + (len(self._mega_queue) if self._mega_queue else 0))
        # observatory snapshots AFTER the engine lock is released
        # (engine -> prof/numerics lock order; see Engine.__init__)
        obs = self.prof.summary(wall_clock())
        ns = (self.numerics.snapshot()
              if self.numerics is not None else None)
        return {"requests": n, **dict(by_status),
                "numerics": self.scfg.numerics,
                "numerics_guard": self.scfg.numerics_guard,
                "steady_lanes": ns["steady_total"] if ns else 0,
                "numerics_violations": (ns["violation_total"]
                                        if ns else 0),
                "prof": self.scfg.prof,
                "cost_model": obs["cost_model"],
                "mem": obs["mem"],
                "slo_burn": obs["slo_burn"],
                "flightrec_dumps": self.tracer.dumps,
                "policy": self.scfg.policy,
                "lane_kernel": self.scfg.lane_kernel,
                "lane_kernel_fallbacks": self.lane_kernel_fallbacks,
                "placement": dict(by_placement),
                "mega_lanes": self.mega_lanes,
                "mega_compiles": self.mega_compiles,
                "queued_now": queued,
                "lane_grows": self.lane_grows,
                "step_compiles": self.step_compiles,
                "tail_compiles": self.tail_compiles,
                "compile_s": round(self.compile_s, 3),
                "dispatch_depth": self.scfg.dispatch_depth,
                "chunks_dispatched": self.chunks_dispatched,
                "tail_chunks": self.tail_chunks,
                "boundary_waits": self.boundary_waits,
                "boundary_wait_s": round(self.boundary_wait_s, 6),
                "device_idle_s": round(self.device_idle_s, 6),
                "lanes_quarantined": self.lanes_quarantined,
                "rollbacks": self.rollbacks,
                "deadline_misses": self.deadline_misses,
                "steady_exits": self.steady_exits,
                "steps_saved": self.steps_saved_total,
                "serve_resumed": self.serve_resumed_total,
                "cache": (self.solvecache.stats()
                          if self.solvecache is not None else None),
                "engine_ckpt_interval": self.scfg.engine_ckpt_interval,
                "engine_ckpt_generation": self._engine_ckpt_gen,
                "shed": self.shed,
                "watchdog_fired": self.watchdog_fired}
