"""Vmapped simulation lanes: the device half of the serving engine.

One compiled program steps up to ``L`` independent solve requests at once.
The requests of one *bucket* (same ndim/dtype/BC, grid side <= the bucket
side ``B``) are stacked into a single ``(L, B+2, ..., B+2)`` array — each
lane carries its request's field in the ``[1 : 1+n]`` corner of a one-cell-
margined bucket buffer, plus per-lane scalars: the stencil coefficient
``r`` (each request's own ``cfg.r``), the request side ``n``, and the
remaining step count. The chunk program runs ``k`` masked steps under
``lax.fori_loop``: every lane computes the full-bucket stencil every step
(shape-stable — the compiled program never depends on which lanes are
live), and a per-lane/per-cell mask decides what is *kept*:

- cells outside the request region keep their old value, so padding never
  contaminates physics;
- a lane whose ``remaining`` counter has hit zero keeps its whole field,
  so lanes finish at exactly their own step count (step-granular, not
  chunk-granular) and idle until the scheduler swaps them.

Bit-identity with solo runs falls out of the masking scheme, not of luck:

- ``edges`` BC: only request-interior cells update; each reads neighbors
  that are all inside the request region — the same values combined in
  the same left-to-right order as ``ops.stencil.ftcs_step_edges``, and
  float add/mul are elementwise IEEE ops that XLA fusion cannot reorder
  per element. The request's frozen boundary ring blocks every read path
  into the padding.
- ``ghost`` BC: every request cell updates, and the loader establishes
  the invariant that ALL padding cells (the margin ring and the unused
  bucket corner) hold ``bc_value``; the mask never lets them update, so a
  request-edge cell reads exactly the conceptual ``bc_value`` ghost ring
  of ``ops.stencil.ftcs_step_ghost``.
- ``periodic`` BC has no padded-bucket form (wraparound would wrap at the
  bucket edge, not the request edge); the scheduler rejects it per
  request instead of letting the engine mis-serve it.

Compile economics: the stepping program is keyed by (bucket, lane-count,
chunk) — the scheduler fixes lane-count and chunk per engine, so serving
any number of requests costs at most ONE stepping compile per bucket x
lane-count, plus one trivial lane-swap program per bucket (the swap takes
the lane index as a traced scalar precisely so refilling lane 3 vs lane 7
is the same executable).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..ops.stencil import accum_dtype_for, laplacian_interior
from ..utils import jnp_dtype

# BC -> first request-interior offset that updates: ghost updates every
# request cell (offset 0), edges freezes the outermost request ring
# (offset 1). periodic is absent by design (see module docstring).
_BC_LO = {"ghost": 0, "edges": 1}


@dataclasses.dataclass(frozen=True)
class BucketKey:
    """What must match for two requests to share a stacked lane array."""

    ndim: int
    n: int        # bucket side: requests with side <= n fit
    dtype: str
    bc: str

    @property
    def padded_shape(self) -> Tuple[int, ...]:
        """Per-lane buffer shape: bucket side + one-cell margin each side
        (the margin is what lets ``laplacian_interior`` see a neighbor for
        every bucket cell, exactly as the ghost/edges solo paths do)."""
        return (self.n + 2,) * self.ndim


def lane_buffer(key: BucketKey, field: np.ndarray, bc_value: float) -> np.ndarray:
    """Host-side lane image of one request: a bucket buffer filled with
    ``bc_value`` (the ghost-BC invariant; harmless fill for edges) with the
    request field written into the ``[1 : 1+n]`` corner."""
    n = field.shape[0]
    if field.shape != (n,) * key.ndim:
        raise ValueError(f"request field {field.shape} is not square/cubic")
    if n > key.n:
        raise ValueError(f"request side {n} exceeds bucket {key.n}")
    buf = np.full(key.padded_shape, bc_value, dtype=np.float64)
    buf[tuple(slice(1, 1 + n) for _ in range(key.ndim))] = np.asarray(
        field, np.float64)
    return buf


def _lane_step(T, r, n, lo: int):
    """One masked FTCS step of a single lane (vmapped over the lane axis).

    ``T``: the padded bucket buffer; the request occupies interior
    coordinates ``0..n-1`` (buffer ``[1:1+n]``). ``r``/``n`` are this
    lane's scalars. Cells with request-interior coordinate in
    ``[lo, n-1-lo]`` along every axis take the stencil update; everything
    else — the frozen edges ring (lo=1), the padding corner, the margin —
    keeps its old value.
    """
    import jax
    import jax.numpy as jnp

    nd = T.ndim
    acc = accum_dtype_for(T.dtype)
    ctr = tuple(slice(1, -1) for _ in range(nd))
    # identical arithmetic to the solo paths: T + r*lap, summed in the
    # reference's left-to-right order by laplacian_interior
    upd = (T[ctr].astype(acc)
           + r.astype(acc) * laplacian_interior(T)).astype(T.dtype)
    mask = None
    for d in range(nd):
        io = jax.lax.broadcasted_iota(jnp.int32, upd.shape, d)
        m = (io >= lo) & (io <= n - 1 - lo)
        mask = m if mask is None else mask & m
    return T.at[ctr].set(jnp.where(mask, upd, T[ctr]))


def make_lane_advance(key: BucketKey):
    """The jitted chunk program for one bucket: ``advance(state, k)`` runs
    ``k`` masked steps over every lane. ``state`` is the flat lane pytree
    ``(fields, r, n, remaining)``; donated, so the double buffer ping-pongs
    like the solo drive loop's."""
    import jax
    import jax.numpy as jnp

    lo = _BC_LO[key.bc]
    step_all = jax.vmap(functools.partial(_lane_step, lo=lo),
                        in_axes=(0, 0, 0))
    ndim = key.ndim

    @functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(0,))
    def advance(state, k: int):
        fields, r, n, remaining = state

        def body(_, carry):
            f, rem = carry
            stepped = step_all(f, r, n)
            act = rem > 0
            f = jnp.where(act.reshape(act.shape + (1,) * ndim), stepped, f)
            return f, rem - act.astype(rem.dtype)

        fields, remaining = jax.lax.fori_loop(0, k, body, (fields, remaining))
        return fields, r, n, remaining

    return advance


def make_lane_loader(key: BucketKey):
    """The jitted lane-swap program: replace lane ``lane`` (a TRACED scalar
    — one compile covers every lane index) with a new request's buffer and
    scalars. Donated like ``advance`` so swapping never copies the other
    lanes."""
    import jax

    @functools.partial(jax.jit, donate_argnums=(0,))
    def load(state, lane, buf, r_new, n_new, steps_new):
        fields, r, n, remaining = state
        fields = jax.lax.dynamic_update_index_in_dim(fields, buf, lane, 0)
        return (fields, r.at[lane].set(r_new), n.at[lane].set(n_new),
                remaining.at[lane].set(steps_new))

    return load


class LaneEngine:
    """Device-side lane state for ONE (bucket, lane-count) combination.

    The scheduler owns admission and swap policy; this class owns the
    arrays and the compiled programs. All methods treat the state
    linearly (every call consumes and replaces it — the buffers are
    donated into each jitted program).
    """

    def __init__(self, key: BucketKey, lanes: int, chunk: int,
                 compiled_cache: Optional[Dict] = None):
        import jax.numpy as jnp

        if key.bc not in _BC_LO:
            raise ValueError(
                f"bc {key.bc!r} has no lane form (periodic wraparound would "
                f"wrap at the bucket edge); supported: {sorted(_BC_LO)}")
        if lanes < 1 or chunk < 1:
            raise ValueError(f"lanes/chunk must be >= 1, got {lanes}/{chunk}")
        self.key = key
        self.lanes = lanes
        self.chunk = chunk
        dt = jnp_dtype(key.dtype)
        acc = accum_dtype_for(dt)
        self._state = (
            jnp.zeros((lanes,) + key.padded_shape, dtype=dt),
            jnp.zeros((lanes,), dtype=acc),          # per-lane r
            jnp.ones((lanes,), dtype=jnp.int32),     # per-lane request side
            jnp.zeros((lanes,), dtype=jnp.int32),    # per-lane steps left
        )
        self._load = make_lane_loader(key)
        # AOT-compile the stepping program (shared across engines through
        # compiled_cache — the scheduler passes one dict per serve run so
        # the (bucket, lane-count) compile really happens at most once)
        self.compile_s = 0.0
        cache = compiled_cache if compiled_cache is not None else {}
        ckey = (key, lanes, chunk)
        if ckey not in cache:
            from ..backends.common import aot_compile_chunks

            advance = make_lane_advance(key)
            compiled, self.compile_s = aot_compile_chunks(
                advance, self._state, [chunk])
            cache[ckey] = compiled[chunk]
        self._advance = cache[ckey]

    # --- lane I/O ---------------------------------------------------------
    def load_lane(self, lane: int, field: np.ndarray, r: float,
                  steps: int, bc_value: float) -> None:
        """Install one request into ``lane``: pad the host field into a
        bucket buffer and swap it in (one traced-index program)."""
        import jax.numpy as jnp

        dt = jnp_dtype(self.key.dtype)
        acc = accum_dtype_for(dt)
        buf = jnp.asarray(lane_buffer(self.key, field, bc_value), dtype=dt)
        self._state = self._load(
            self._state, jnp.int32(lane), buf,
            jnp.asarray(r, acc), jnp.int32(field.shape[0]),
            jnp.int32(steps))

    def extract_lane(self, lane: int, n: int) -> np.ndarray:
        """Fetch one finished lane's request field to host (D2H of a single
        lane; the scheduler hands the result to the async writeback)."""
        buf = np.asarray(self._state[0][lane])
        return buf[tuple(slice(1, 1 + n) for _ in range(self.key.ndim))]

    # --- stepping ---------------------------------------------------------
    def step_chunk(self) -> np.ndarray:
        """Run one ``chunk``-step program over every lane; returns the
        per-lane remaining-step counts (host, (L,) int32 — the only fetch
        the boundary needs). The fetch doubles as the chunk fence."""
        self._state = self._advance(self._state)
        return np.asarray(self._state[3])

    def remaining(self) -> np.ndarray:
        return np.asarray(self._state[3])


def wall_clock() -> float:
    """Seam for tests; the scheduler stamps queue/serve waits with this."""
    return time.perf_counter()
