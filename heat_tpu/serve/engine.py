"""Vmapped simulation lanes: the device half of the serving engine.

One compiled program steps up to ``L`` independent solve requests at once.
The requests of one *bucket* (same ndim/dtype/BC, grid side <= the bucket
side ``B``) are stacked into a single ``(L, B+2, ..., B+2)`` array — each
lane carries its request's field in the ``[1 : 1+n]`` corner of a one-cell-
margined bucket buffer, plus per-lane scalars: the stencil coefficient
``r`` (each request's own ``cfg.r``), the request side ``n``, and the
remaining step count. The chunk program runs ``k`` masked steps under
``lax.fori_loop``: every lane computes the full-bucket stencil every step
(shape-stable — the compiled program never depends on which lanes are
live), and a per-lane/per-cell mask decides what is *kept*:

- cells outside the request region keep their old value, so padding never
  contaminates physics;
- a lane whose ``remaining`` counter has hit zero keeps its whole field,
  so lanes finish at exactly their own step count (step-granular, not
  chunk-granular) and idle until the scheduler swaps them.

Bit-identity with solo runs falls out of the masking scheme, not of luck:

- ``edges`` BC: only request-interior cells update; each reads neighbors
  that are all inside the request region — the same values combined in
  the same left-to-right order as ``ops.stencil.ftcs_step_edges``, and
  float add/mul are elementwise IEEE ops that XLA fusion cannot reorder
  per element. The request's frozen boundary ring blocks every read path
  into the padding.
- ``ghost`` BC: every request cell updates, and the loader establishes
  the invariant that ALL padding cells (the margin ring and the unused
  bucket corner) hold ``bc_value``; the mask never lets them update, so a
  request-edge cell reads exactly the conceptual ``bc_value`` ghost ring
  of ``ops.stencil.ftcs_step_ghost``.
- ``periodic`` BC has no padded-bucket form (wraparound would wrap at the
  bucket edge, not the request edge); the scheduler rejects it per
  request instead of letting the engine mis-serve it.

Compile economics: the stepping program is keyed by (bucket, lane-count,
chunk) — the scheduler fixes lane-count and chunk per engine, so serving
any number of requests costs at most ONE stepping compile per bucket x
lane-count, plus one trivial lane-swap program per bucket (the swap takes
the lane index as a traced scalar precisely so refilling lane 3 vs lane 7
is the same executable). Lane counts are rounded up to power-of-two
*tiers* (``lane_tier``) so waves of 3 and then 5 requests under the same
``--lanes`` cap land on one compiled program instead of two, and a lazily
compiled *tail* program (``chunk // 4`` steps) bounds the masked waste
when every live lane is about to finish — one tail compile per
(bucket, lane-tier), only paid when a tail is actually dispatched.

Dispatch discipline (the PR-4 rework): stepping no longer fences.
``dispatch_chunk`` enqueues one chunk program and returns a *device*
handle to the post-chunk remaining-step vector without any host
round-trip; ``fetch_remaining`` is the only boundary D2H, and the
scheduler calls it on a handle whose chunk was dispatched one or more
chunks ago — the transfer overlaps the chunks queued behind it. The
per-lane scalars (r, side, remaining) are deliberately NOT donated into
the chunk program so an old remaining-handle stays valid while newer
chunks consume the field stack; only the (L, B+2, ...) field buffer —
the allocation that matters — ping-pongs through donation.

Per-lane fault domains (the ISSUE-5 rework): the chunk program
additionally reduces each lane's post-chunk field to a per-lane
``isfinite`` bit and returns an int32 *boundary vector* — row 0 the
remaining-step counts, row 1 the finite bits — so the health verdict
rides the boundary fetch the scheduler already pays for, with no extra
D2H and no change to what the lanes compute (the reduction reads the
fields; it never writes them, so bit-identity is untouched).

Numerics telemetry on the boundary (the ISSUE-15 rework): the boundary
vector is ``(K_BOUNDARY, L)`` = ``(6, L)`` int32 — rows 0–1 the
remaining/finite pair above, unchanged, and rows 2–5 four per-lane
float32 solution-quality statistics BITCAST into the int32 carrier
(``pack_boundary``/``unpack_boundary``): the interior ``max|ΔT|`` over
the chunk's final mini-step (steady-state residual), the
request-region min and max (the discrete-maximum-principle witnesses),
and the total heat content ``ΣT``. Both chunk bodies compute them
fused into the reductions they already run (the XLA body peels the
final ``fori_loop`` step to hold the pre-step stack; the Pallas kernel
accumulates them in the SMEM pass next to the isfinite bit), so
solution-quality telemetry costs zero extra sweeps, zero extra
transfers, and zero change to the field bytes. The stats rows are
always computed (no recompile dimension); ``ServeConfig.numerics``
gates only host-side ingestion (runtime/numerics.py).
``fetch_remaining`` optionally wraps the transfer in a watchdog
(``runtime/async_io.bounded_call``): a wedged device fetch becomes a
clean ``BoundedFetchTimeout`` the scheduler turns into per-request
failures instead of a hung ``heat-tpu serve``.

Pallas-native lane stepping (the ISSUE-9 rework): the chunk program has
two interchangeable bodies — the vmapped masked XLA stencil above (the
bit-exactness ORACLE) and the multi-lane Pallas kernel family
(``ops/pallas_stencil.lane_multistep``): the lane axis becomes a grid
dimension over the solo hand-tuned halo-slab/3x3 plans, with the
per-lane interior mask, the per-lane countdown gate, AND the per-lane
``isfinite`` health reduction fused into the stencil pass itself — lane
health costs zero extra sweeps over the stack. ``resolve_lane_kernel``
maps the ``--serve-lane-kernel auto|pallas|xla`` knob to a backend per
bucket (auto = Pallas on TPU where a kernel plan exists); an
unavailable Pallas program degrades to XLA as a structured
``lane_kernel_fallback`` record + counter, never an error. Rollback
mode additionally drops donation (``donate=False``) so the undonated
input stack of each chunk IS the previous boundary's snapshot — the
old per-chunk full-stack copy program is gone from the dispatch path
entirely (``snapshot_stack``).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from ..ops.stencil import accum_dtype_for, laplacian_interior
from ..utils import jnp_dtype

# BC -> first request-interior offset that updates: ghost updates every
# request cell (offset 0), edges freezes the outermost request ring
# (offset 1). periodic is absent by design (see module docstring).
_BC_LO = {"ghost": 0, "edges": 1}


def host_fetch(x) -> np.ndarray:
    """The ONE device->host fetch seam of the serve hot path.

    Every boundary inspection and lane extraction funnels through here so
    tests can monkeypatch it to prove the dispatch path never fences
    (ISSUE 4 regression contract) and to count fetches per boundary."""
    # heat-tpu: allow[hot-path-purity] THE sanctioned D2H seam itself
    return np.asarray(x)


# The per-lane boundary vector's row layout (ISSUE 15). Rows 0-1 are
# plain int32 (the original remaining/finite pair — every consumer's
# ``rem, finite = b[0], b[1]`` reads them unchanged); rows 2-5 are
# float32 statistics bitcast into the int32 carrier so ONE array — one
# dispatch output, one D2H — carries progress, health, and solution
# quality per lane per chunk.
BOUNDARY_ROWS = ("remaining", "finite", "resid", "tmin", "tmax", "heat")
K_BOUNDARY = len(BOUNDARY_ROWS)


def pack_boundary(remaining, finite, stats):
    """Device-side boundary assembly: stack the int32 remaining/finite
    rows over the ``(4, L)`` float32 stats block bitcast to int32 (a
    free reinterpret — no rounding, NaN/Inf payloads survive exactly).
    The inverse is ``unpack_boundary`` on the fetched host array."""
    import jax
    import jax.numpy as jnp

    bits = jax.lax.bitcast_convert_type(stats.astype(jnp.float32),
                                        jnp.int32)
    head = jnp.stack([remaining, finite.astype(remaining.dtype)])
    return jnp.concatenate([head, bits], axis=0)


def unpack_boundary(b: np.ndarray) -> np.ndarray:
    """Host-side view of a fetched ``(K_BOUNDARY, L)`` boundary vector's
    stats block: rows 2-5 reinterpreted as float32 — ``(4, L)`` ordered
    (resid, tmin, tmax, heat) per BOUNDARY_ROWS. A bit-level view, not
    a conversion; the int32 head rows are read directly as ``b[0]``,
    ``b[1]`` by every consumer."""
    return np.ascontiguousarray(b[2:K_BOUNDARY]).view(np.float32)


def _lane_stats(prev, fields, n, ndim: int):
    """Per-lane float32 solution-quality stats over the request region.

    The region mask covers buffer coordinates ``[1, n_lane]`` along every
    axis — the full request field INCLUDING its Dirichlet ring (the
    maximum principle bounds interior values by ``[min(IC, bc),
    max(IC, bc)]``, so the witnesses must see the boundary cells), and
    never the padding corner or the margin. Reductions run in float32
    (the bf16 accumulation discipline of ``accum_dtype_for``); they read
    the stacks and write nothing, so field bytes are untouched."""
    import jax
    import jax.numpy as jnp

    lanes = fields.shape[0]
    f32 = fields.astype(jnp.float32)
    mask = None
    for d in range(ndim):
        io = jax.lax.broadcasted_iota(jnp.int32, fields.shape, d + 1)
        nl = n.reshape((lanes,) + (1,) * ndim)
        m = (io >= 1) & (io <= nl)
        mask = m if mask is None else mask & m
    axes = tuple(range(1, ndim + 1))
    delta = jnp.abs(f32 - prev.astype(jnp.float32))
    resid = jnp.max(jnp.where(mask, delta, jnp.float32(0)), axis=axes)
    tmin = jnp.min(jnp.where(mask, f32, jnp.float32(jnp.inf)), axis=axes)
    tmax = jnp.max(jnp.where(mask, f32, jnp.float32(-jnp.inf)), axis=axes)
    heat = jnp.sum(jnp.where(mask, f32, jnp.float32(0)), axis=axes)
    return jnp.stack([resid, tmin, tmax, heat])


def lane_tier(needed: int, cap: int) -> int:
    """Round a wave's lane need up to the next power-of-two tier, capped
    at the configured lane budget. Waves of 3 then 5 requests under
    ``cap=4`` both land on tier 4 — one compiled stepping program where
    ``min(lanes, len(q))`` would have compiled two."""
    if needed < 1 or cap < 1:
        raise ValueError(f"needed/cap must be >= 1, got {needed}/{cap}")
    t = 1
    while t < needed:
        t <<= 1
    return min(cap, t)


# The dimensions of the compiled-stepping-program cache key, in order.
# `heat-tpu audit`'s compile-budget contract reads chunk_cache_key's
# signature and compares it against the budget declared in
# analysis/digests/programs.json — adding a recompile dimension here
# without updating the declared budget fails the audit instead of
# shipping a production compile storm (the PR-4 one-compile-per-combo
# guarantee, made mechanical).
STEP_KEY_DIMS = ("bucket", "lanes", "k", "kernel", "donate")


def chunk_cache_key(bucket: BucketKey, lanes: int, k: int, kernel: str,
                    donate: bool) -> tuple:
    """The ONE cache key under which a compiled lane stepping program is
    stored (LaneEngine._ensure). Every distinct value of this tuple is a
    distinct XLA executable; the audit enumerates this function's image
    over a ServeConfig to bound total compiles."""
    return (bucket, lanes, k, kernel, donate)


def tail_size(chunk: int) -> Optional[int]:
    """Size of the one precompiled tail program per (bucket, lane-tier):
    a quarter chunk (>= 1). When every live lane's remaining count drops
    below ``chunk``, stepping ``ceil(rem / tail)`` tail chunks computes at
    most ``rem + tail - 1`` masked steps instead of a full ``chunk`` —
    bounded waste for one extra (lazily compiled) program. ``None`` for
    chunk 1, where a tail cannot be smaller than the chunk."""
    return chunk // 4 if chunk >= 4 else (1 if chunk > 1 else None)


@dataclasses.dataclass(frozen=True)
class BucketKey:
    """What must match for two requests to share a stacked lane array."""

    ndim: int
    n: int        # bucket side: requests with side <= n fit
    dtype: str
    bc: str

    @property
    def padded_shape(self) -> Tuple[int, ...]:
        """Per-lane buffer shape: bucket side + one-cell margin each side
        (the margin is what lets ``laplacian_interior`` see a neighbor for
        every bucket cell, exactly as the ghost/edges solo paths do)."""
        return (self.n + 2,) * self.ndim


def lane_buffer(key: BucketKey, field: np.ndarray, bc_value: float) -> np.ndarray:
    """Host-side lane image of one request: a bucket buffer filled with
    ``bc_value`` (the ghost-BC invariant; harmless fill for edges) with the
    request field written into the ``[1 : 1+n]`` corner."""
    n = field.shape[0]
    if field.shape != (n,) * key.ndim:
        raise ValueError(f"request field {field.shape} is not square/cubic")
    if n > key.n:
        raise ValueError(f"request side {n} exceeds bucket {key.n}")
    buf = np.full(key.padded_shape, bc_value, dtype=np.float64)
    buf[tuple(slice(1, 1 + n) for _ in range(key.ndim))] = np.asarray(
        field, np.float64)
    return buf


def _lane_step(T, r, n, lo: int):
    """One masked FTCS step of a single lane (vmapped over the lane axis).

    ``T``: the padded bucket buffer; the request occupies interior
    coordinates ``0..n-1`` (buffer ``[1:1+n]``). ``r``/``n`` are this
    lane's scalars. Cells with request-interior coordinate in
    ``[lo, n-1-lo]`` along every axis take the stencil update; everything
    else — the frozen edges ring (lo=1), the padding corner, the margin —
    keeps its old value.
    """
    import jax
    import jax.numpy as jnp

    nd = T.ndim
    acc = accum_dtype_for(T.dtype)
    ctr = tuple(slice(1, -1) for _ in range(nd))
    # identical arithmetic to the solo paths: T + r*lap, summed in the
    # reference's left-to-right order by laplacian_interior
    upd = (T[ctr].astype(acc)
           + r.astype(acc) * laplacian_interior(T)).astype(T.dtype)
    mask = None
    for d in range(nd):
        io = jax.lax.broadcasted_iota(jnp.int32, upd.shape, d)
        m = (io >= lo) & (io <= n - 1 - lo)
        mask = m if mask is None else mask & m
    return T.at[ctr].set(jnp.where(mask, upd, T[ctr]))


def make_lane_advance(key: BucketKey, kernel: str = "xla",
                      donate: bool = True):
    """The jitted chunk program for one bucket: ``advance(fields, r, n,
    remaining, k)`` runs ``k`` masked steps over every lane and returns
    the new state plus the ``(K_BOUNDARY, L)`` boundary vector —
    per-lane remaining steps, ``isfinite`` bits, and the four bitcast
    numerics stats rows (``BOUNDARY_ROWS``), the one array a chunk
    boundary needs to fetch to judge progress, health, AND solution
    quality of every lane.

    ``kernel`` picks the stepping body: ``"xla"`` — the vmapped masked
    stencil under ``lax.fori_loop`` (the serving ORACLE: every other
    backend must match it byte for byte); ``"pallas"`` — the multi-lane
    Pallas kernel family (``ops/pallas_stencil.lane_multistep``: lane
    axis as a grid dimension over the solo halo-slab/3x3 plans, per-lane
    interior mask + countdown gate + isfinite reduction fused into the
    stencil pass, so lane health costs zero extra sweeps). Both bodies
    produce the same remaining-count algebra (``max(rem - k, 0)``) and
    bit-identical fields — gate ``"pallas"`` on ``resolve_lane_kernel``.

    ``donate=True`` donates only the field stack (the buffer that
    matters — it ping-pongs like the solo drive loop's double buffer);
    the per-lane scalars and the boundary vector are left undonated on
    purpose, so a boundary handle taken after chunk ``i`` survives while
    chunks ``i+1..`` are dispatched behind it — the foundation of the
    dispatch-ahead boundary (scheduler.py). ``donate=False`` is rollback
    mode's contract: the undonated input stack IS the previous
    boundary's snapshot, so keeping boundaries restorable costs no
    standalone copy program on the dispatch path (see
    ``LaneEngine.snapshot_stack``)."""
    import jax
    import jax.numpy as jnp

    lo = _BC_LO[key.bc]
    ndim = key.ndim
    donate_argnums = (0,) if donate else ()

    if kernel == "pallas":
        from ..ops.pallas_stencil import lane_multistep

        bucket_n = key.n

        @functools.partial(jax.jit, static_argnums=(4,),
                           donate_argnums=donate_argnums)
        def advance(fields, r, n, remaining, k: int):
            # mask + countdown gate + health reduction + numerics stats
            # all live INSIDE the kernel passes; remaining's update is
            # the same O(L) algebra the fori_loop body produces step by
            # step
            fields, finite, stats = lane_multistep(
                fields, r, n, remaining, k, bc_lo=lo, bucket_n=bucket_n)
            remaining = jnp.maximum(remaining - k, 0)
            boundary = pack_boundary(remaining, finite, stats)
            return fields, r, n, remaining, boundary

        return advance

    step_all = jax.vmap(functools.partial(_lane_step, lo=lo),
                        in_axes=(0, 0, 0))

    @functools.partial(jax.jit, static_argnums=(4,),
                       donate_argnums=donate_argnums)
    def advance(fields, r, n, remaining, k: int):
        def body(_, carry):
            f, rem = carry
            stepped = step_all(f, r, n)
            act = rem > 0
            f = jnp.where(act.reshape(act.shape + (1,) * ndim), stepped, f)
            return f, rem - act.astype(rem.dtype)

        # the final mini-step is peeled out of the loop so the pre-step
        # stack stays in scope for the residual stat — the SAME body,
        # the same per-step elementwise IEEE arithmetic, so the field
        # bytes are untouched (k == 1: the loop is a no-op)
        prev, remaining = jax.lax.fori_loop(0, k - 1, body,
                                            (fields, remaining))
        fields, remaining = body(k - 1, (prev, remaining))
        # per-lane health: one bit per lane, reduced on device — padding
        # cells hold bc_value (finite) and masking confines a NaN to its
        # own lane, so a zero bit is that lane's fault and only its own
        finite = jnp.isfinite(fields).reshape(fields.shape[0], -1).all(axis=1)
        stats = _lane_stats(prev, fields, n, ndim)
        boundary = pack_boundary(remaining, finite, stats)
        return fields, r, n, remaining, boundary

    return advance


def make_lane_loader(key: BucketKey, donate: bool = True):
    """The jitted lane-swap program: replace lane ``lane`` (a TRACED scalar
    — one compile covers every lane index) with a new request's buffer and
    scalars. With ``donate=True`` the field stack is donated like
    ``advance``'s so swapping never copies the other lanes; rollback-mode
    engines pass ``donate=False`` because live boundary snapshots alias
    the stack (donating it would invalidate them — admissions then pay
    one stack copy, chunk dispatch still pays none). The scalar vectors
    are tiny and stay undonated for the same handle-liveness reason."""
    import jax

    donate_argnums = (0,) if donate else ()

    @functools.partial(jax.jit, donate_argnums=donate_argnums)
    def load(fields, r, n, remaining, lane, buf, r_new, n_new, steps_new):
        fields = jax.lax.dynamic_update_index_in_dim(fields, buf, lane, 0)
        return (fields, r.at[lane].set(r_new), n.at[lane].set(n_new),
                remaining.at[lane].set(steps_new))

    return load


def resolve_lane_kernel(requested: str, key: BucketKey):
    """Resolve the ``--serve-lane-kernel`` knob for ONE bucket into the
    backend a lane engine will actually run, plus a fallback reason when
    the resolution is a degradation the operator should hear about.

    Returns ``(kernel, reason)``: ``kernel`` in {"pallas", "xla"};
    ``reason`` is None for a clean resolution and a human string when a
    requested/expected Pallas program is unavailable — the scheduler
    turns that into a structured ``lane_kernel_fallback`` record plus a
    counter, never an error (the XLA lane program is the bit-exact
    oracle; only throughput differs). Rules: ``"xla"`` — always XLA;
    ``"pallas"`` — Pallas when a kernel plan exists for the bucket
    (f64 has none: no TPU VPU f64; nor do 3D buckets whose band fits no
    VMEM plan), loud XLA fallback otherwise; ``"auto"`` — Pallas on TPU
    when a plan exists, XLA elsewhere (off-TPU the Pallas interpreter
    loses to the fused XLA program — that is policy, not a fallback)."""
    if requested == "xla" or key.bc not in _BC_LO:
        return "xla", None
    import jax

    from ..ops.pallas_stencil import lane_kernel_available

    avail = lane_kernel_available(key.ndim, key.n, key.dtype)
    if not avail:
        reason = ("float64 has no Pallas lane kernel (no f64 on the TPU "
                  "VPU)" if key.dtype == "float64" else
                  f"no VMEM-feasible lane band for a {key.ndim}d bucket "
                  f"of side {key.n}")
    if requested == "pallas":
        return ("pallas", None) if avail else ("xla", reason)
    # auto: Pallas exactly where it is the measured win — on TPU
    if jax.default_backend() != "tpu":
        return "xla", None
    return ("pallas", None) if avail else ("xla", reason)


class LaneEngine:
    """Device-side lane state for ONE (bucket, lane-tier) combination.

    The scheduler owns admission, dispatch depth, and swap policy; this
    class owns the arrays and the compiled programs. All methods treat
    the field stack linearly (every stepping/loading call consumes and
    replaces it — the buffer is donated into each jitted program).

    Stepping programs (the steady ``chunk`` and the optional ``tail``)
    compile lazily through ``_ensure`` against a shared ``compiled_cache``
    keyed by (bucket, lane-tier, k); ``on_compile(k, seconds)`` fires for
    every program actually built so the scheduler's compile accounting
    (one stepping compile per combo, plus at most one tail) stays exact.
    """

    def __init__(self, key: BucketKey, lanes: int, chunk: int,
                 compiled_cache: Optional[Dict] = None,
                 on_compile: Optional[Callable[[int, float], None]] = None,
                 kernel: str = "xla", donate: bool = True):
        import jax.numpy as jnp

        if key.bc not in _BC_LO:
            raise ValueError(
                f"bc {key.bc!r} has no lane form (periodic wraparound would "
                f"wrap at the bucket edge); supported: {sorted(_BC_LO)}")
        if lanes < 1 or chunk < 1:
            raise ValueError(f"lanes/chunk must be >= 1, got {lanes}/{chunk}")
        if kernel not in ("xla", "pallas"):
            raise ValueError(f"kernel must be 'xla' or 'pallas' (resolve "
                             f"'auto' via resolve_lane_kernel), got "
                             f"{kernel!r}")
        self.key = key
        self.lanes = lanes
        self.chunk = chunk
        self.kernel = kernel
        self.donate = donate
        self.tail = tail_size(chunk)
        if kernel == "pallas":
            from ..ops.pallas_stencil import lane_state_shape

            shape = lane_state_shape(key.ndim, key.n, key.dtype)
            if shape is None:
                raise ValueError(
                    f"no Pallas lane kernel plan for bucket {key} — gate "
                    f"construction on resolve_lane_kernel")
            # the stack lives in the kernel's padded layout for the whole
            # engine lifetime (alignment padding is frozen by the
            # per-lane bounds and never read by a live cell), so chunk
            # dispatch pays zero per-call pad/crop; the request still
            # occupies the [1 : 1+n] corner, so extraction is unchanged
            self._lane_shape = shape
        else:
            self._lane_shape = key.padded_shape
        dt = jnp_dtype(key.dtype)
        acc = accum_dtype_for(dt)
        self._state = (
            jnp.zeros((lanes,) + self._lane_shape, dtype=dt),
            jnp.zeros((lanes,), dtype=acc),          # per-lane r
            jnp.ones((lanes,), dtype=jnp.int32),     # per-lane request side
            jnp.zeros((lanes,), dtype=jnp.int32),    # per-lane steps left
        )
        self._load = make_lane_loader(key, donate=donate)
        self._advance_fn = make_lane_advance(key, kernel=kernel,
                                             donate=donate)
        self._cache = compiled_cache if compiled_cache is not None else {}
        self._on_compile = on_compile
        self.compile_s = 0.0
        # the steady chunk program compiles up front (before any request
        # is admitted into a lane) — the tail program waits for first use
        self._ensure(chunk)

    def _ensure(self, k: int):
        """Compiled executable for a k-step program, built at most once
        per (bucket, lane-tier, k, kernel, donation mode) across the
        scheduler's shared cache (rollback-mode programs donate nothing
        and are distinct executables from the donating default)."""
        ckey = chunk_cache_key(self.key, self.lanes, k, self.kernel,
                               self.donate)
        if ckey not in self._cache:
            from ..backends.common import aot_compile_chunks

            # the compile-observatory key (runtime/prof.py): which lane
            # program this was — bucket geometry x tier x kernel, steady
            # vs tail k — so the structured compile log attributes lazy
            # tail/tier compiles to the group that forced them
            compiled, spent = aot_compile_chunks(
                self._advance_fn, self._state, [k],
                label=(f"lanes {self.key.ndim}d n{self.key.n} "
                       f"{self.key.dtype} {self.key.bc} L{self.lanes}"),
                kernel=self.kernel)
            self._cache[ckey] = compiled[k]
            self.compile_s += spent
            if self._on_compile is not None:
                self._on_compile(k, spent)
        return self._cache[ckey]

    # --- lane I/O ---------------------------------------------------------
    def load_lane(self, lane: int, field: np.ndarray, r: float,
                  steps: int, bc_value: float) -> None:
        """Install one request into ``lane``: pad the host field into a
        bucket buffer and swap it in (one traced-index program).

        The buffer and scalars are converted with NUMPY and handed to the
        jitted loader raw: every ``jnp.asarray``/``jnp.int32`` here would
        be an eager device op — a python-dispatch round trip per argument
        per admission, plus a one-time XLA compile per (shape, dtype) —
        on the serve hot path. The loader's own dispatch does the H2D.
        (numpy handles the bfloat16 cast through ml_dtypes, with the same
        round-to-nearest-even the XLA convert would apply.)"""
        dt = jnp_dtype(self.key.dtype)
        acc = accum_dtype_for(dt)
        buf = lane_buffer(self.key, field, bc_value).astype(dt)
        if buf.shape != self._lane_shape:
            # pallas layout: embed the bucket buffer in the kernel-aligned
            # slab corner; the zero alignment padding is frozen by the
            # per-lane bounds (finite, never read by a live cell)
            slab = np.zeros(self._lane_shape, dtype=dt)
            slab[tuple(slice(0, s) for s in buf.shape)] = buf
            buf = slab
        self._state = self._load(
            *self._state, np.int32(lane), buf,
            np.asarray(r, acc), np.int32(field.shape[0]),
            np.int32(steps))

    def snapshot_lane(self, lane: int):
        """One-lane ON-DEVICE copy of a finished lane (the PR-1 snapshot
        trick, one lane wide): enqueued behind whatever chunks are in
        flight and detached from the donation chain, so stepping resumes
        immediately and the writer thread fetches at its leisure."""
        from ..runtime.async_io import lane_snapshot

        return lane_snapshot(self._state[0], lane)

    def extract(self, snap, n: int) -> np.ndarray:
        """D2H a lane snapshot and crop it to the request's field. This is
        the transfer the dispatch-ahead rework moved OFF the scheduler
        thread — call it from the writer thread."""
        buf = host_fetch(snap)
        return buf[tuple(slice(1, 1 + n) for _ in range(self.key.ndim))]

    def extract_lane(self, lane: int, n: int) -> np.ndarray:
        """Synchronous one-lane fetch (the --dispatch-depth off fallback
        and library spelunking; blocks on every chunk in flight)."""
        return self.extract(self.snapshot_lane(lane), n)

    # --- stepping ---------------------------------------------------------
    def dispatch_chunk(self, k: Optional[int] = None):
        """Enqueue one k-step program (default: the steady chunk) over
        every lane and return a DEVICE handle to the post-chunk
        ``(K_BOUNDARY, L)`` boundary vector (remaining steps, per-lane
        finite bits, bitcast numerics stats) — no host round trip, no
        fence. The handle stays valid under later dispatches because it
        is never donated."""
        fn = self._ensure(self.chunk if k is None else k)
        out = fn(*self._state)
        self._state = out[:4]
        return out[4]

    def fetch_remaining(self, handle, timeout_s: Optional[float] = None,
                        plan=None, fetch_index: int = 0) -> np.ndarray:
        """The boundary D2H: fetch a ``(K_BOUNDARY, L)`` boundary handle
        to host (row 0 remaining steps, row 1 finite bits, rows 2-5 the
        bitcast numerics stats — ``unpack_boundary``). With dispatch depth
        > 1 the scheduler calls this on a chunk dispatched one or more
        chunks ago, so the transfer (and the bookkeeping it gates) hides
        under the chunks queued behind it.

        ``timeout_s`` arms the fetch watchdog: the transfer runs in an
        abandonable thread and a wedged device surfaces as
        ``async_io.BoundedFetchTimeout`` (the scheduler fails that
        group's requests cleanly) instead of hanging the serve loop
        forever. ``plan`` is the active fault plan — the ``fetch-hang``
        injection sleeps INSIDE the watchdogged region, so chaos tests
        exercise the exact production path."""
        return fetch_boundary(handle, timeout_s=timeout_s, plan=plan,
                              fetch_index=fetch_index)

    def step_chunk(self, timeout_s: Optional[float] = None, plan=None,
                   fetch_index: int = 0) -> np.ndarray:
        """Dispatch one steady chunk and immediately fetch its boundary
        vector — the synchronous boundary (``--dispatch-depth off``); the
        fetch doubles as the chunk fence."""
        return self.fetch_remaining(self.dispatch_chunk(),
                                    timeout_s=timeout_s, plan=plan,
                                    fetch_index=fetch_index)

    def remaining(self) -> np.ndarray:
        return np.asarray(self._state[3])

    # --- per-lane fault domains (ISSUE 5) ---------------------------------
    def poison_lane(self, lane: int, n: int) -> None:
        """Chaos-only (``lane-nan`` injection): flip the center cell of
        ``lane``'s request region to NaN. An eager scatter enqueued after
        the chunks already in flight — deterministic in device order, and
        never reached without an active fault plan (hot-path invariant:
        no fault spec, no call)."""
        import jax.numpy as jnp

        idx = (lane,) + tuple(1 + n // 2 for _ in range(self.key.ndim))
        f, r, nn, rem = self._state
        self._state = (f.at[idx].set(jnp.nan), r, nn, rem)

    def perturb_lane(self, lane: int, n: int, eps: float) -> None:
        """Chaos-only (``perturb`` injection, ISSUE 15): add a bounded
        bump ``eps`` to the center cell of ``lane``'s request region —
        finite, so the isfinite bit stays green, but (for any eps above
        the detector tolerance) outside the maximum-principle envelope:
        the numerics observatory's quarry rather than the nonfinite
        path's. Same eager-scatter shape as ``poison_lane``; never
        reached without an active fault plan."""
        import jax.numpy as jnp

        idx = (lane,) + tuple(1 + n // 2 for _ in range(self.key.ndim))
        f, r, nn, rem = self._state
        self._state = (f.at[idx].add(jnp.asarray(eps, f.dtype)), r, nn, rem)

    def snapshot_stack(self):
        """The post-chunk lane stack as a restorable boundary snapshot
        (``--serve-on-nan rollback`` bookkeeping): a lane judged finite
        at that boundary can later be restored from its row.

        Rollback-mode engines are built ``donate=False``, so the live
        stack handle taken here IS a stable snapshot — no later advance
        or load consumes its buffer, and keeping every in-flight
        boundary restorable dispatches NO standalone copy program (the
        pre-rework shape paid one full-stack on-device copy per
        dispatched chunk). At most one buffer stays live per in-flight
        boundary: exactly the advance outputs the pipeline holds anyway.
        A donating engine (where a scheduler never calls this on the
        dispatch path) still gets the defensive on-device copy."""
        if not self.donate:
            return self._state[0]
        from ..runtime.async_io import device_snapshot

        return device_snapshot(self._state[0])

    def restore_lane(self, lane: int, buf, r: float, n: int,
                     steps: int) -> None:
        """Roll ONE lane back to a verified-finite boundary: reuse the
        traced-index loader with an on-device row (no H2D, no new
        compile), resetting the lane's field and its remaining count
        while every other lane is untouched. ``buf`` is not donated, so
        the same snapshot row survives a second rollback attempt."""
        dt = jnp_dtype(self.key.dtype)
        acc = accum_dtype_for(dt)
        self._state = self._load(
            *self._state, np.int32(lane), buf,
            np.asarray(r, acc), np.int32(n), np.int32(steps))


def fetch_boundary(handle, timeout_s: Optional[float] = None, plan=None,
                   fetch_index: int = 0) -> np.ndarray:
    """The ONE watchdogged boundary-D2H path, shared by the packed lane
    engine (``LaneEngine.fetch_remaining``) and the sharded mega-lane
    (``MegaLaneEngine``): fetch a ``(K_BOUNDARY, L)`` boundary handle to
    host, optionally under the ``bounded_call`` watchdog, with the
    ``fetch-hang`` fault injection firing INSIDE the watchdogged region
    either way (runtime/faults.py)."""
    def fetch():
        if plan is not None:
            plan.maybe_fetch_hang(fetch_index)
        # heat-tpu: allow[hot-path-purity] the watchdogged boundary D2H
        return host_fetch(handle)

    if timeout_s is None:
        return fetch()
    from ..runtime.async_io import bounded_call

    return bounded_call(fetch, timeout_s, "serve boundary fetch")


class MegaLaneEngine:
    """Device half of ONE mesh-spanning mega-lane occupant.

    The second placement tier (ISSUE 10): a request that overflows every
    bucket runs as a *sharded mega-lane* — the whole device mesh executes
    the ``backends/sharded.py`` padded-carry chunked advance for that one
    request, wrapped in the exact dispatch contract ``LaneEngine``
    exposes for packed lanes: ``dispatch_chunk(k)`` enqueues one k-step
    program and returns a DEVICE handle to a ``(K_BOUNDARY, 1)``
    boundary vector (remaining steps, an owned-cells ``isfinite`` bit,
    and the bitcast numerics stats reduced over the owned interior) with
    no host round trip; the scheduler's ``fetch_boundary`` is the only D2H; the
    carried padded state is donated through each chunk like the solo
    drive's double buffer. One mega-lane is therefore just a bucket
    group of lane-count one whose "bucket" is the mesh.

    Bit-exactness is inherited, not hoped: the chunk body IS
    ``make_mega_machinery``'s wrap of the solo padded-carry blocks
    (same exchange, same kernel, same bounds), the initial state is the
    same device-built IC + seed the solo path resolves, and owned-cell
    values are invariant under chunk partitioning (the fused-exchange
    margin argument) — so serving in ``--chunk``-step slices produces
    the byte-identical field a solo ``drive()`` of the same config
    yields in one call.

    Compile economics: the seed/crop programs and every chunk size this
    occupant will run (the steady ``chunk`` plus at most one remainder)
    are AOT-compiled at admission through the engine-shared cache, keyed
    by (config geometry, mesh, k) — re-admitting the same oversized
    config costs zero compiles, and nothing ever compiles inside the
    dispatch loop."""

    def __init__(self, cfg, mesh, chunk: int,
                 compiled_cache: Optional[Dict] = None,
                 on_compile: Optional[Callable[[int, float], None]] = None):
        self.cfg = cfg
        self.mesh = mesh
        self.chunk = chunk
        self._cache = compiled_cache if compiled_cache is not None else {}
        self._on_compile = on_compile
        # geometry + physics fields that select a distinct compiled
        # program family (r folds in sigma/nu/dom_len/n; exchange and
        # local_kernel shape the shard body)
        self._ckey = ("mega", cfg.ndim, cfg.n, cfg.dtype, cfg.bc,
                      repr(cfg.bc_value), repr(float(cfg.r)),
                      tuple(mesh.devices.shape), cfg.exchange, cfg.comm,
                      cfg.local_kernel, cfg.fuse_steps)
        self._label = (f"mega {cfg.ndim}d n{cfg.n} {cfg.dtype} {cfg.bc} "
                       f"mesh {'x'.join(map(str, mesh.devices.shape))}")
        m = self._machinery()
        self.kf = m["kf"]
        self._advance = m["advance"]
        self._seed_c = m["seed"]
        self._crop_c = m["crop"]
        for k in self.chunk_sizes():
            self._ensure(k)
        self.reload()

    # --- compiled-program plumbing ----------------------------------------
    def _structs(self, kf: int):
        """(owned, padded) ShapeDtypeStructs the seed/crop programs
        compile against — the same derivation the sharded compile guard
        uses (``_probe_state_struct``)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        cfg, mesh = self.cfg, self.mesh
        sharding = NamedSharding(mesh, P(*mesh.axis_names))
        dt = jnp_dtype(cfg.dtype)
        owned = jax.ShapeDtypeStruct(cfg.shape, dt, sharding=sharding)
        padded = jax.ShapeDtypeStruct(
            tuple(cfg.n + 2 * kf * int(s) for s in mesh.devices.shape),
            dt, sharding=sharding)
        return owned, padded

    def _machinery(self) -> dict:
        """Build (or fetch warm) the mega machinery for this (config,
        mesh): the jitted advance plus AOT-compiled seed/crop programs.
        Cached engine-wide so a second admission of the same oversized
        config compiles nothing."""
        key = ("mega-mach",) + self._ckey
        m = self._cache.get(key)
        if m is None:
            from ..backends.sharded import make_mega_machinery
            from ..runtime import prof

            t0 = time.perf_counter()
            seed, advance, crop, kf = make_mega_machinery(self.cfg,
                                                          self.mesh)
            owned, padded = self._structs(kf)
            m = {"kf": kf, "advance": advance,
                 "seed": seed.lower(owned).compile(),
                 "crop": crop.lower(padded).compile()}
            spent = time.perf_counter() - t0
            self._cache[key] = m
            prof.compile_log().note(self._label + " seed/crop", 0, spent)
            if self._on_compile is not None:
                self._on_compile(0, spent)
        return m

    def chunk_sizes(self) -> list:
        """Every k the occupant's drain will dispatch: the steady chunk
        plus at most one remainder (the solo drive's chunk_sizes shape,
        with the serve chunk as the event interval)."""
        ntime = self.cfg.ntime
        if ntime <= 0:
            return []
        k0 = min(self.chunk, ntime)
        sizes = {k0}
        if ntime % k0:
            sizes.add(ntime % k0)
        return sorted(sizes)

    def _ensure(self, k: int):
        ckey = self._ckey + (k,)
        if ckey not in self._cache:
            from ..backends.common import aot_compile_chunks

            import jax

            _, padded = self._structs(self.kf)
            rem = jax.ShapeDtypeStruct((1,), np.int32)
            compiled, spent = aot_compile_chunks(
                self._advance, (padded, rem), [k], label=self._label,
                kernel="sharded")
            self._cache[ckey] = compiled[k]
            if self._on_compile is not None:
                self._on_compile(k, spent)
        return self._cache[ckey]

    # --- state lifecycle --------------------------------------------------
    def reload(self) -> None:
        """(Re)build the carried padded state from the deterministic
        initial condition — admission, and the rollback path's
        no-verified-boundary-yet restart. The IC is the device-built,
        mesh-sharded construction the solo sharded drive resolves, so
        the starting bytes match a solo run's exactly."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..grid import initial_condition_device

        sharding = NamedSharding(self.mesh, P(*self.mesh.axis_names))
        T0 = initial_condition_device(self.cfg, sharding=sharding)
        self._state = self._seed_c(T0)
        del T0
        self._rem = np.asarray([self.cfg.ntime], np.int32)

    def load(self, T, steps_left: int) -> None:
        """Seed the carried padded state from a HOST field with
        ``steps_left`` steps to go — engine-state resume (serve
        --resume). Owned-cell values are invariant under chunk
        partitioning (the fused-exchange margin argument the solo
        sharded drive rides), so seeding from a cropped checkpoint
        field at a chunk boundary continues bit-identically."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(self.mesh, P(*self.mesh.axis_names))
        T_dev = jax.device_put(
            np.asarray(T, dtype=jnp_dtype(self.cfg.dtype)), sharding)
        self._state = self._seed_c(T_dev)
        del T_dev
        self._rem = np.asarray([int(steps_left)], np.int32)

    def dispatch_chunk(self, k: int):
        """Enqueue one k-step mesh program and return the DEVICE handle
        to its ``(K_BOUNDARY, 1)`` boundary vector — no fence, no host
        round trip (the mega mirror of ``LaneEngine.dispatch_chunk``)."""
        fn = self._ensure(k)
        self._state, self._rem, boundary = fn(self._state, self._rem)
        return boundary

    def snapshot_state(self):
        """Restorable on-device copy of the carried state (rollback
        bookkeeping). The mega state IS donated through each chunk (the
        whole point of padded-carry), so unlike the packed lanes'
        aliasing trick this pays one device-side copy per dispatched
        chunk — only in rollback mode, the PR-5 pre-rework shape."""
        from ..runtime.async_io import device_snapshot

        return device_snapshot(self._state)

    def restore(self, snap, steps_left: int) -> None:
        """Roll the mega-lane back to a verified-finite boundary. The
        snapshot is copied in (not adopted): a second rollback attempt
        must find it intact."""
        from ..runtime.async_io import device_snapshot

        self._state = device_snapshot(snap)
        self._rem = np.asarray([steps_left], np.int32)

    def final_snapshot(self):
        """Crop the padded carried state to the owned global field — a
        device program enqueued behind whatever is in flight; the D2H
        happens in the writer thread via ``extract``."""
        return self._crop_c(self._state)

    @staticmethod
    def extract(snap) -> np.ndarray:
        """D2H a cropped final field (writer thread). Static on purpose:
        the writeback closure must not pin the multi-shard padded state
        alive, only the cropped snapshot."""
        return host_fetch(snap)

    def poison_center(self) -> None:
        """Chaos-only (``lane-nan`` injection on a mega request): NaN the
        center OWNED cell of the carried padded state. Device placement
        is re-pinned to the state's sharding so the compiled advance's
        input layout contract survives the eager scatter."""
        import jax
        import jax.numpy as jnp

        cfg, kf = self.cfg, self.kf
        idx = []
        for s in self.mesh.devices.shape:
            local = cfg.n // int(s)
            shard, off = divmod(cfg.n // 2, local)
            idx.append(shard * (local + 2 * kf) + kf + off)
        poisoned = self._state.at[tuple(idx)].set(jnp.nan)
        self._state = jax.device_put(poisoned, self._state.sharding)

    def perturb_center(self, eps: float) -> None:
        """Chaos-only (``perturb`` injection on a mega request, ISSUE 15):
        add a bounded bump to the center owned cell — finite (the isfinite
        bit stays green) but outside the maximum-principle envelope for
        any eps above the detector tolerance. Same placement re-pin as
        ``poison_center``."""
        import jax
        import jax.numpy as jnp

        cfg, kf = self.cfg, self.kf
        idx = []
        for s in self.mesh.devices.shape:
            local = cfg.n // int(s)
            shard, off = divmod(cfg.n // 2, local)
            idx.append(shard * (local + 2 * kf) + kf + off)
        bumped = self._state.at[tuple(idx)].add(
            jnp.asarray(eps, self._state.dtype))
        self._state = jax.device_put(bumped, self._state.sharding)


def wall_clock() -> float:
    """Seam for tests; the scheduler stamps queue/serve waits with this."""
    return time.perf_counter()


# --- program-registry seam (ISSUE 13) ----------------------------------------
# Every program family the lane/mega engines compile, as abstract
# ProgramSpecs: `heat-tpu audit` traces and lowers them on shape structs
# (no engine, no device state, no execution) to machine-check donation,
# purity, dtype discipline, and digest drift. Keep this list in lockstep
# with what the engines actually build — a family missing here is a
# family the audit cannot see.

def _lane_structs(key: BucketKey, lanes: int, kernel: str = "xla"):
    """Abstract (fields, r, n, remaining) argument structs for one lane
    engine's programs — the exact shapes/dtypes LaneEngine.__init__
    allocates, including the Pallas kernel's padded slab layout."""
    import jax

    dt = jnp_dtype(key.dtype)
    acc = accum_dtype_for(dt)
    if kernel == "pallas":
        from ..ops.pallas_stencil import lane_state_shape

        shape = lane_state_shape(key.ndim, key.n, key.dtype)
    else:
        shape = key.padded_shape
    return (jax.ShapeDtypeStruct((lanes,) + shape, dt),
            jax.ShapeDtypeStruct((lanes,), acc),
            jax.ShapeDtypeStruct((lanes,), np.int32),
            jax.ShapeDtypeStruct((lanes,), np.int32))


def lane_program_specs():
    """Every packed-lane program family (stepping XLA/Pallas, rollback,
    tail, loader) at a representative bucket — small enough to trace in
    seconds, wide enough that each contract family has a real subject."""
    from ..analysis.programs import ProgramSpec
    from ..ops.pallas_stencil import lane_kernel_available

    B, L, K = 64, 4, 8

    def _advance_build(key, kernel, donate, k):
        def build():
            adv = make_lane_advance(key, kernel=kernel, donate=donate)
            return adv, _lane_structs(key, L, kernel) + (k,), (4,)
        return build

    def _loader_build(key):
        def build():
            import jax

            dt = jnp_dtype(key.dtype)
            acc = accum_dtype_for(dt)
            load = make_lane_loader(key, donate=True)
            args = _lane_structs(key, L) + (
                jax.ShapeDtypeStruct((), np.int32),
                jax.ShapeDtypeStruct(key.padded_shape, dt),
                jax.ShapeDtypeStruct((), acc),
                jax.ShapeDtypeStruct((), np.int32),
                jax.ShapeDtypeStruct((), np.int32))
            return load, args, ()
        return build

    def _spec(dtype, bc, kernel="xla", donate=True, k=K, tag=""):
        key = BucketKey(2, B, dtype, bc)
        name = f"lane/{kernel}/2d/n{B}/{dtype}/{bc}{tag}"
        return ProgramSpec(
            name=name, build=_advance_build(key, kernel, donate, k),
            donated=(0,) if donate else (), no_alias=not donate,
            dtype=dtype, storage_round=(dtype == "bfloat16"), steps=k,
            lanes=L, kernel=kernel, family="lane",
            bucket=f"2d/n{B}/{dtype}/{bc}")

    specs = [
        _spec("float32", "edges"),
        # rollback mode: the undonated input stack IS the boundary
        # snapshot (PR 9) — the audit proves it never aliases an output
        _spec("float32", "edges", donate=False, tag="/rollback"),
        _spec("float32", "edges", k=tail_size(16), tag="/tail"),
        _spec("bfloat16", "edges"),
    ]
    for dtype in ("float32", "bfloat16"):
        if lane_kernel_available(2, B, dtype):
            specs.append(_spec(dtype, "edges", kernel="pallas"))
    key3 = BucketKey(3, 16, "float32", "ghost")
    specs.append(ProgramSpec(
        name="lane/xla/3d/n16/float32/ghost",
        build=_advance_build(key3, "xla", True, K), donated=(0,),
        dtype="float32", steps=K, lanes=L, kernel="xla", family="lane",
        bucket="3d/n16/float32/ghost"))
    key = BucketKey(2, B, "float32", "edges")
    specs.append(ProgramSpec(
        name=f"lane/load/2d/n{B}/float32/edges", build=_loader_build(key),
        donated=(0,), dtype="float32", steps=0, lanes=L, kernel="xla",
        family="loader"))
    return specs


def mega_program_specs():
    """The sharded mega-lane chunk program (ISSUE 10) on a 1x1 mesh —
    mesh-shape-pinned so the digest is stable on any host; real meshes
    change shard counts, not the contract set."""
    from ..analysis.programs import ProgramSpec
    from ..config import HeatConfig

    def build():
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..backends.sharded import make_mega_machinery
        from ..parallel.mesh import build_mesh

        cfg = HeatConfig(n=32, ndim=2, dtype="float32", bc="ghost",
                         ntime=16, backend="sharded", mesh_shape=(1, 1))
        mesh = build_mesh(cfg.ndim, cfg.mesh_shape)
        _, advance, _, kf = make_mega_machinery(cfg, mesh)
        sharding = NamedSharding(mesh, P(*mesh.axis_names))
        padded = jax.ShapeDtypeStruct(
            tuple(cfg.n + 2 * kf * int(s) for s in mesh.devices.shape),
            jnp_dtype(cfg.dtype), sharding=sharding)
        rem = jax.ShapeDtypeStruct((1,), np.int32)
        return advance, (padded, rem, 8), (2,)

    return [ProgramSpec(
        name="mega/sharded/2d/n32/float32/ghost", build=build,
        donated=(0,), dtype="float32", steps=8, lanes=1,
        kernel="sharded", family="mega")]
