"""Pluggable admission policies + SLO accounting for the serving engine.

PR 3-5 built an engine whose admission queue was a hard-coded FIFO deque:
correct for a batch drain, but an *online* service (serve/gateway.py) has
tenants with different urgency, and the ``deadline_ms`` plumbing PR 5
added only ever *shed* late requests — it never shaped who runs next.
This module extracts the queue behind a small policy interface so the
scheduler stops caring about ordering:

- ``fifo`` — a deque, pop order bit-identical to the pre-policy engine
  (regression-locked in tests/test_serve_policy.py against a lane-
  assignment trace captured from the PR-5 scheduler). The default: solo
  ``heat-tpu serve --requests`` behaves exactly as before.
- ``edf`` — earliest-deadline-first *within* an SLO class, classes in
  priority order (``config.SLO_CLASSES``: interactive < standard <
  batch). Requests without a deadline sort after every dated request of
  their class; submit order breaks ties, so ``edf`` degrades to ``fifo``
  when nobody sets deadlines. This is the Orca/vLLM-shaped admission
  story: deadlines shape *ordering*, not just shedding.
- ``fair`` — weighted fair share *across tenants* (start-time-style
  virtual time: each tenant accumulates served work divided by its
  weight; the next admission goes to the backlogged tenant with the
  least normalized service), EDF-within-class *inside* each tenant.
  A flooding tenant cannot starve another past its weight ratio, and a
  tenant returning from idle is capped to the current virtual time so it
  cannot hoard credit while away.

Thread-safety contract: queue objects are NOT internally locked — every
push/pop happens under the engine's one lock (scheduler.py), which also
keeps the per-tenant queue-depth counters consistent with the queues.

The tiny Prometheus-shaped ``Histogram`` the gateway's ``/metrics``
surface exports (per-class latency, queue depth) moved to
``runtime/prof.py`` with the rest of the observatory primitives (PR 8);
it is re-exported here so every existing ``policy_mod.Histogram``
consumer keeps working.
"""

from __future__ import annotations

import collections
import heapq
import math
from typing import Dict, List, Optional, Tuple

from ..config import SLO_CLASSES
from ..runtime.prof import (DEPTH_BUCKETS, LATENCY_BUCKETS,  # noqa: F401
                            Histogram)

POLICIES = ("fifo", "edf", "fair")


def _predicted_rank(req) -> float:
    """Predicted-finish rank (semantic scheduling, ISSUE 16): an
    ``until=steady`` request with a closed-form eigenmode ETA
    (``Request.predicted_steps``, runtime/convergence.py) ranks by that
    predicted step count — shortest-predicted-job-first among otherwise
    equal peers. Fixed-step requests (and steady requests without a
    finite prediction) rank ``+inf``, so every pre-existing ordering —
    classes first, earliest deadline, FIFO among undated peers — is
    preserved bit-for-bit."""
    pred = getattr(req, "predicted_steps", None)
    if getattr(req, "until", "steps") != "steady" or pred is None:
        return math.inf
    return float(pred)


def _edf_key(req) -> Tuple[int, float, float, int]:
    """(class priority, deadline, predicted finish, submit seq): classes
    strictly first, earliest absolute deadline inside a class, then the
    predicted-finish rank (see ``_predicted_rank`` — +inf unless an
    until=steady request carries an ETA), FIFO among the rest (deadline
    +inf). ``req.seq`` is the engine-wide submit counter, so the
    ordering is total and deterministic."""
    deadline = req.deadline_t if req.deadline_t is not None else math.inf
    return (SLO_CLASSES.get(req.slo_class, max(SLO_CLASSES.values())),
            deadline, _predicted_rank(req), req.seq)


class FifoQueue:
    """The pre-policy behavior, verbatim: pop in submit order."""

    def __init__(self):
        self._q = collections.deque()

    def push(self, req) -> None:
        self._q.append(req)

    def pop(self):
        return self._q.popleft() if self._q else None

    def items(self) -> List:
        """Non-destructive snapshot of every queued request (engine-state
        checkpointing reads the queue without disturbing pop order)."""
        return list(self._q)

    def __len__(self) -> int:
        return len(self._q)

    def __bool__(self) -> bool:
        return bool(self._q)


class EdfQueue:
    """Class-priority + earliest-deadline-first heap (see module doc)."""

    def __init__(self):
        self._h: List[Tuple[Tuple[int, float, int], object]] = []

    def push(self, req) -> None:
        heapq.heappush(self._h, (_edf_key(req), req))

    def pop(self):
        return heapq.heappop(self._h)[1] if self._h else None

    def items(self) -> List:
        """Snapshot of queued requests (heap order, NOT pop order — the
        checkpoint replays them through push() again, which re-sorts)."""
        return [entry[1] for entry in self._h]

    def __len__(self) -> int:
        return len(self._h)

    def __bool__(self) -> bool:
        return bool(self._h)


class FairShareQueue:
    """Weighted fair share across tenants, EDF-within-class per tenant.

    Classic virtual-time WFQ over request *work* (``points * steps`` —
    a tenant of many small requests and a tenant of few huge ones get
    wall-proportional shares, not request-count-proportional): popping a
    tenant's request advances that tenant's virtual time by
    ``work / weight``; the next pop serves the backlogged tenant with
    the smallest virtual time (tenant name breaks exact ties, so the
    order is deterministic). A tenant whose queue just went non-empty is
    raised to the minimum active virtual time — returning from idle must
    not replay banked credit and lock everyone else out.
    """

    def __init__(self, weights: Optional[Dict[str, float]] = None):
        self._weights = dict(weights or {})
        self._tenants: Dict[str, List] = {}   # tenant -> EDF heap
        self._vtime: Dict[str, float] = {}
        self._count = 0

    def _weight(self, tenant: str) -> float:
        return float(self._weights.get(tenant, 1.0))

    def push(self, req) -> None:
        h = self._tenants.get(req.tenant)
        if h is None:
            h = self._tenants[req.tenant] = []
        if not h:
            # idle -> backlogged: catch up to the busiest floor
            active = [self._vtime[t] for t, q in self._tenants.items()
                      if q and t != req.tenant]
            floor = min(active) if active else 0.0
            self._vtime[req.tenant] = max(
                self._vtime.get(req.tenant, 0.0), floor)
        heapq.heappush(h, (_edf_key(req), req))
        self._count += 1

    def pop(self):
        live = [(self._vtime[t], t) for t, h in self._tenants.items() if h]
        if not live:
            return None
        _, tenant = min(live)
        req = heapq.heappop(self._tenants[tenant])[1]
        self._count -= 1
        # fair-share charges PREDICTED work where a prediction exists
        # (an until=steady request is expected to stop early — billing
        # nominal steps would under-schedule its tenant); actual usage
        # still lands in the ledger at retirement (runtime/prof.py)
        steps = req.cfg.ntime
        pred = getattr(req, "predicted_steps", None)
        if getattr(req, "until", "steps") == "steady" and pred is not None:
            steps = min(steps, pred)
        work = float(req.cfg.points * max(steps, 1))
        self._vtime[tenant] += work / self._weight(tenant)
        return req

    def items(self) -> List:
        """Snapshot of every tenant's queued requests (unordered; resume
        re-pushes them, rebuilding the heaps. Virtual-time credit is NOT
        part of the snapshot — a resumed engine restarts every tenant at
        vtime 0, the same already-fair state a fresh engine starts in)."""
        return [entry[1] for h in self._tenants.values() for entry in h]

    def __len__(self) -> int:
        return self._count

    def __bool__(self) -> bool:
        return self._count > 0


# --- admission tracing (runtime/trace.py) ------------------------------------
# Queue objects stay trace-free (they are pure ordering structures); the
# scheduler calls these at its push/pop sites so every policy's admission
# decisions land on the timeline the same way: an ``enqueue`` instant per
# push and an id-paired ``queue-wait`` span per pop — per tenant, so one
# tenant's overlapping waits stack on one track and a starved tenant is a
# visibly empty one.

def note_enqueue(tracer, policy: str, req) -> None:
    tracer.instant("enqueue", tracer.track("queue", req.tenant),
                   cat="queue", trace_id=req.trace_id,
                   args={"id": req.id, "policy": policy,
                         "class": req.slo_class}, ts=req.submit_t)


def note_pop(tracer, policy: str, req, now: float) -> None:
    tracer.async_span("queue-wait", tracer.track("queue", req.tenant),
                      req.submit_t, now, req.trace_id,
                      args={"id": req.id, "policy": policy,
                            "tenant": req.tenant, "class": req.slo_class})


def make_queue(policy: str, tenant_weights=()):
    """One admission queue for one bucket group under ``policy``."""
    if policy == "fifo":
        return FifoQueue()
    if policy == "edf":
        return EdfQueue()
    if policy == "fair":
        return FairShareQueue(dict(tenant_weights))
    raise ValueError(f"policy must be one of {POLICIES}, got {policy!r}")


# --- /metrics primitives -----------------------------------------------------
# Histogram / LATENCY_BUCKETS / DEPTH_BUCKETS live in runtime/prof.py now
# (re-exported above).
