"""Request JSONL contract + the ``heat-tpu serve`` entry point.

A requests file is JSON Lines: one JSON object per line, blank lines and
``#`` comment lines ignored. Each object is a solve request; keys map to
the same-named ``HeatConfig`` fields (``config.config_from_request``):

    {"id": "a", "n": 128, "ntime": 500}
    {"id": "b", "n": 300, "ntime": 200, "nu": 0.1, "dtype": "float32",
     "bc": "ghost", "bc_value": 1.0, "ic": "uniform", "deadline_ms": 5000}

``id`` is optional (auto-assigned ``req-NNNN``); ``deadline_ms`` is an
optional per-request wall budget from submission (overrides the engine
default ``--serve-deadline``; an over-deadline lane is preempted at its
next chunk boundary with status ``deadline``); everything else defaults
to the ``HeatConfig`` defaults. Unknown keys are a per-request rejection
(typos must not silently serve different physics). The engine pads each
request up to the smallest configured bucket side and serves same-bucket
requests as vmapped lanes under dispatch-ahead continuous batching (see
scheduler.py / engine.py); execution knobs — ``--lanes``, ``--chunk``,
``--buckets``, ``--dispatch-depth``, ``--serve-on-nan``, ``--max-queue``,
``--fetch-watchdog`` — are engine policy, never request payload.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Optional, Tuple

from ..config import HeatConfig, config_from_request
from .scheduler import Engine, ServeConfig


def load_requests(path) -> List[Tuple[Optional[str], Optional[HeatConfig],
                                      Optional[float], Optional[str]]]:
    """Parse a requests JSONL file into ``(id, cfg, deadline_ms,
    parse_error)`` tuples.

    A malformed line yields ``(id-or-None, None, None, reason)`` instead
    of raising: one bad request must not take down the whole file (the
    same per-request isolation contract the engine applies at admission).
    A non-positive ``deadline_ms`` is a parse error (the engine would
    reject it at submit — fail it at the same per-request granularity).
    """
    out = []
    for lineno, raw in enumerate(Path(path).read_text().splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        rid = None
        try:
            d = json.loads(line)
            if not isinstance(d, dict):
                raise ValueError(f"request must be a JSON object, got "
                                 f"{type(d).__name__}")
            rid = d.get("id")
            deadline_ms = d.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
                if deadline_ms <= 0:
                    raise ValueError(
                        f"deadline_ms must be > 0, got {deadline_ms}")
            out.append((rid, config_from_request(d), deadline_ms, None))
        except Exception as e:  # noqa: BLE001 — recorded per request
            out.append((rid, None, None,
                        f"line {lineno}: {type(e).__name__}: {e}"))
    return out


def serve_requests(path, scfg: ServeConfig = ServeConfig(),
                   engine: Optional[Engine] = None) -> Tuple[List[dict], dict]:
    """Serve every request in a JSONL file; returns (records, summary).

    Parse failures become status='rejected' records alongside the engine's
    own admission rejections, so the records list covers every input line.
    """
    eng = engine or Engine(scfg)
    parse_failures = []
    for i, (rid, cfg, deadline_ms, err) in enumerate(load_requests(path)):
        if cfg is None:
            rec = {"id": rid or f"line-{i}", "status": "rejected",
                   "error": err}
            parse_failures.append(rec)
            if scfg.emit_records:
                from ..runtime.logging import json_record

                json_record("serve_request", **rec)
            continue
        eng.submit(cfg, request_id=rid, deadline_ms=deadline_ms)
    records = eng.results() + parse_failures
    summary = eng.summary()
    summary["requests"] += len(parse_failures)
    if parse_failures:
        summary["rejected"] = summary.get("rejected", 0) + len(parse_failures)
    return records, summary
