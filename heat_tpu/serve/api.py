"""Request contract (JSONL file + HTTP body lines) and the offline
``heat-tpu serve`` entry point.

A requests file is JSON Lines: one JSON object per line, blank lines and
``#`` comment lines ignored. Each object is a solve request; keys map to
the same-named ``HeatConfig`` fields (``config.config_from_request``):

    {"id": "a", "n": 128, "ntime": 500}
    {"id": "b", "n": 300, "ntime": 200, "nu": 0.1, "dtype": "float32",
     "bc": "ghost", "bc_value": 1.0, "ic": "uniform", "deadline_ms": 5000,
     "tenant": "acme", "class": "interactive"}

``id`` is optional (auto-assigned ``req-NNNN``); ``deadline_ms`` is an
optional per-request wall budget from submission (overrides the engine
default ``--serve-deadline``; an over-deadline lane is preempted at its
next chunk boundary with status ``deadline`` — and under ``--policy edf``
the deadline also shapes *admission order*); ``tenant`` and ``class``
(``config.SLO_CLASSES``: interactive | standard | batch) are the SLO
fields the fair-share/EDF policies and the per-tenant quota key on;
``until`` picks the completion semantics (``steps`` runs exactly
``ntime`` steps, ``steady`` retires the lane early once its residual
EWMA passes the steady tolerance — per-request ``tol``, else the engine
``--steady-tol`` — with ``ntime`` as the hard cap; see
``config.validate_until_fields``). Everything else defaults to the
``HeatConfig`` defaults. Unknown keys are
a per-request rejection (typos must not silently serve different
physics). The engine pads each request up to the smallest configured
bucket side and serves same-bucket requests as vmapped lanes under
dispatch-ahead continuous batching (see scheduler.py / engine.py);
execution knobs — ``--lanes``, ``--chunk``, ``--buckets``,
``--dispatch-depth``, ``--serve-on-nan``, ``--max-queue``,
``--fetch-watchdog``, ``--policy``, ``--tenant-weights``,
``--tenant-quota`` — are engine policy, never request payload.

The HTTP gateway (serve/gateway.py) POSTs the exact same line format to
``/v1/solve``; both front doors parse through ``parse_request_obj`` so a
request means one thing no matter how it arrives.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import List, Optional, Tuple

from ..config import (HeatConfig, config_from_request, validate_slo_fields,
                      validate_until_fields)
from .scheduler import Engine, ServeConfig


@dataclasses.dataclass
class ParsedRequest:
    """One parsed request line: either a submittable (cfg + scheduler
    fields) or a per-line parse failure (``error`` set, cfg None)."""

    id: Optional[str] = None
    cfg: Optional[HeatConfig] = None
    deadline_ms: Optional[float] = None
    tenant: Optional[str] = None
    slo_class: Optional[str] = None
    until: str = "steps"
    tol: Optional[float] = None
    error: Optional[str] = None


def parse_request_obj(d) -> ParsedRequest:
    """Validate one request object (already JSON-decoded) into a
    ``ParsedRequest``. Never raises: a malformed request is that
    request's rejection, not its neighbors' (the per-request isolation
    contract both the JSONL file and the HTTP batch body rely on)."""
    rid = None
    try:
        if not isinstance(d, dict):
            raise ValueError(f"request must be a JSON object, got "
                             f"{type(d).__name__}")
        rid = d.get("id")
        if rid is not None:
            rid = str(rid)
        deadline_ms = d.get("deadline_ms")
        if deadline_ms is not None:
            deadline_ms = float(deadline_ms)
            if deadline_ms <= 0:
                raise ValueError(
                    f"deadline_ms must be > 0, got {deadline_ms}")
        tenant, slo_class = validate_slo_fields(d.get("tenant"),
                                                d.get("class"))
        until, tol = validate_until_fields(d.get("until"), d.get("tol"))
        return ParsedRequest(id=rid, cfg=config_from_request(d),
                             deadline_ms=deadline_ms, tenant=tenant,
                             slo_class=slo_class, until=until, tol=tol)
    except Exception as e:  # noqa: BLE001 — recorded per request
        return ParsedRequest(id=rid, error=f"{type(e).__name__}: {e}")


def load_requests(path) -> List[ParsedRequest]:
    """Parse a requests JSONL file into ``ParsedRequest`` rows.

    A malformed line yields a row with ``error`` set instead of raising:
    one bad request must not take down the whole file (the same
    per-request isolation contract the engine applies at admission).
    """
    out = []
    for lineno, raw in enumerate(Path(path).read_text().splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        try:
            d = json.loads(line)
        except Exception as e:  # noqa: BLE001 — recorded per request
            out.append(ParsedRequest(
                error=f"line {lineno}: {type(e).__name__}: {e}"))
            continue
        row = parse_request_obj(d)
        if row.error is not None:
            row.error = f"line {lineno}: {row.error}"
        out.append(row)
    return out


def submit_parsed(eng: Engine, row: ParsedRequest) -> str:
    """Submit one successfully parsed row (shared by the offline drain
    and the gateway). ``row.cfg`` must be set."""
    return eng.submit(row.cfg, request_id=row.id,
                      deadline_ms=row.deadline_ms, tenant=row.tenant,
                      slo_class=row.slo_class, until=row.until, tol=row.tol)


def serve_requests(path, scfg: Optional[ServeConfig] = None,
                   engine: Optional[Engine] = None,
                   skip_ids=()) -> Tuple[List[dict], dict]:
    """Serve every request in a JSONL file; returns (records, summary).

    Parse failures become status='rejected' records alongside the engine's
    own admission rejections, so the records list covers every input line.
    ``scfg`` defaults to ``ServeConfig()`` (resolved per call, not at
    definition — the B008 mutable-default-adjacent footgun ruff now
    gates). ``skip_ids`` (``serve --resume``) names requests already
    recovered from — or finished before — an engine-state checkpoint;
    matching file rows are not re-submitted (the resume replay is the
    authority on their state, including mid-solve progress).
    """
    scfg = scfg if scfg is not None else ServeConfig()
    eng = engine or Engine(scfg)
    skip_ids = frozenset(skip_ids)
    parse_failures = []
    for i, row in enumerate(load_requests(path)):
        if row.id is not None and row.id in skip_ids:
            continue
        if row.cfg is None:
            rec = {"id": row.id or f"line-{i}", "status": "rejected",
                   "error": row.error}
            parse_failures.append(rec)
            if scfg.emit_records:
                from ..runtime.logging import json_record

                json_record("serve_request", **rec)
            continue
        submit_parsed(eng, row)
    records = eng.results() + parse_failures
    summary = eng.summary()
    summary["requests"] += len(parse_failures)
    if parse_failures:
        summary["rejected"] = summary.get("rejected", 0) + len(parse_failures)
    return records, summary
