"""Online serving gateway: streaming HTTP admission over the lane engine.

``heat-tpu serve --listen HOST:PORT`` turns the PR-3..5 batch drain into
a long-running service. The engine's scheduler runs on its own thread
(``Engine.start()``); this module is the stdlib-only front door that
feeds it while lanes run and exposes the operational surface an online
system owes its operators:

- ``POST /v1/solve`` — newline-delimited JSON request objects (the exact
  ``serve --requests`` line format, ``serve/api.py``). Default response
  is a chunked ``application/x-ndjson`` stream: one record line per
  request, written the moment that request's lane retires (iteration-
  level admission is only *online* because of this — a request arriving
  mid-chunk is admitted at the next boundary). ``?wait=0`` returns 202
  with the accepted ids immediately; poll instead.
- ``GET /v1/requests/<id>`` — one record snapshot (404 unknown id);
  ``?field=1`` inlines the final field as JSON lists — the read the
  canary prober (serve/probe.py) verifies solutions through.
- ``GET /healthz`` — 200 while admitting, 503 once draining (the flip a
  load balancer keys on), plus a scheduler-crash indicator.
- ``POST /drainz`` — graceful drain: stops admission (healthz flips
  immediately, new solves get 503), lets every in-flight lane and queued
  request finish, then shuts the scheduler down. Idempotent; repeated
  calls report progress.
- ``GET /metrics`` — Prometheus text format: request counters by status,
  per-tenant queue-depth gauges, per-class end-to-end latency histograms
  and the queue-depth-at-submit histogram (serve/policy.py), plus every
  counter ``Engine.summary()`` tracks (quarantines, rollbacks, deadline
  misses, shed, watchdog, compiles, boundary waits), build identity
  (``heat_tpu_build_info``) and process uptime. User-supplied label
  values (tenant/class) are escaped per the exposition format.
- ``GET /tracez`` — the engine's event ring (runtime/trace.py) as Chrome
  trace-event JSON, on demand: load it straight into Perfetto to see
  lane occupancy, chunk pipelining, and queue waits of the live engine.
  Every response to ``/v1/solve`` echoes the minted per-request trace
  ids in an ``X-Trace-Id`` header (and every NDJSON record carries its
  ``trace_id``), so client logs join against the timeline.
- ``GET /statusz`` — human-readable operator snapshot (text): engine
  counters, the online chunk-cost model (runtime/prof.py), compile
  observatory, memory watermarks, SLO burn rates, top tenants by usage,
  flight-recorder dump paths. The "what is this server doing right now"
  page; everything on it is also machine-readable elsewhere.
- ``GET /v1/usage`` — the per-tenant usage ledger as JSON: lane-seconds,
  steps, chunks, and bytes written per (tenant, class) plus engine-wide
  totals, reconciling exactly with the ``usage`` stamps on the
  per-request records (``heat-tpu usage URL`` renders it as a table).

**Every** response carries an ``X-Trace-Id`` header — success, 4xx/5xx
error paths, ``/drainz``, all of it: the inbound header is echoed when
the client sent one (charset-checked), else an id is minted, so a
client log line always joins against the server's trace no matter how
the request ended. ``/v1/solve`` responses override the default with
the per-request ids they minted.

Backpressure is the PR-5 machinery made visible: a submit shed by
``--max-queue`` or ``--tenant-quota`` answers **429 with Retry-After**
instead of queueing without bound, and a draining gateway answers 503
with the same header. Per-lane fault domains flow through unchanged — a
quarantined lane's request streams back as a structured ``nonfinite``
record over HTTP, exactly the record the JSONL drain would have printed.

Threading model: ``ThreadingHTTPServer`` handler threads call only the
engine's thread-safe surface (``submit``/``poll``/``wait``/listeners);
the scheduler thread never blocks on a socket. Result streaming is
listener-driven (no polling loops): each streaming POST registers a
results listener, submits, then relays matching records from a local
queue until its batch completes.
"""

from __future__ import annotations

import json
import queue as queue_lib
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from ..config import SLO_CLASSES
from ..runtime import debug
from ..runtime import prof as prof_mod
from ..runtime import trace as trace_mod
from ..runtime.logging import master_print
from .api import parse_request_obj, submit_parsed
from .scheduler import Engine, TERMINAL_STATUSES

MAX_BODY_BYTES = 16 << 20   # one POST body; a solve request is ~100 bytes,
                            # so this bounds even absurd batch lines
_OVERLOAD_PREFIX = "overloaded:"

# Inbound X-Trace-Id values we will echo verbatim: ids we mint plus any
# sane client-correlation token. Anything else (header-splitting
# attempts, binary junk) is replaced by a freshly minted id.
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9._,-]{1,200}$")


def escape_label_value(v) -> str:
    """Escape one Prometheus label VALUE per the text exposition format:
    backslash, double-quote, and newline must be escaped — ``tenant`` and
    ``class`` are user-supplied request strings, and a tenant named
    ``a"b`` (or one smuggling a newline) must corrupt its own label, not
    the whole scrape."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def render_metrics(engine: Engine) -> str:
    """The ``/metrics`` payload (Prometheus text exposition format).

    Pure function of the engine so tests can assert on it without a
    socket; the gateway handler just serves it."""
    s = engine.summary()
    out = []

    def metric(name, mtype, help_text, samples):
        out.append(f"# HELP {name} {help_text}")
        out.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            lbl = ("{" + ",".join(
                f'{k}="{escape_label_value(v)}"' for k, v in labels) + "}"
                   if labels else "")
            out.append(f"{name}{lbl} {value}")

    import jax

    from .. import __version__

    metric("heat_tpu_build_info", "gauge",
           "Build/runtime identity (value is always 1).",
           [([("version", __version__), ("jax", jax.__version__),
              ("backend", jax.default_backend())], 1)])
    metric("heat_tpu_process_uptime_seconds", "gauge",
           "Seconds since this serving process started.",
           [([], round(trace_mod.process_uptime_s(), 3))])
    metric("heat_tpu_serve_info", "gauge",
           "Static engine configuration (value is always 1).",
           [([("policy", s["policy"]),
              ("dispatch_depth", s["dispatch_depth"]),
              ("classes", "|".join(sorted(SLO_CLASSES,
                                          key=SLO_CLASSES.get)))], 1)])
    metric("heat_tpu_serve_draining", "gauge",
           "1 once /drainz has been called (healthz returns 503).",
           [([], int(engine.draining))])
    metric("heat_tpu_serve_scheduler_up", "gauge",
           "1 while the online scheduler thread is alive and healthy.",
           [([], int(engine.online and engine.loop_error is None))])
    metric("heat_tpu_serve_requests_total", "counter",
           "Requests ever submitted, by current/terminal status.",
           [([("status", st)], s[st]) for st in
            (*TERMINAL_STATUSES, "queued", "running") if s.get(st)]
           or [([("status", "ok")], 0)])
    metric("heat_tpu_serve_requests_by_placement_total", "counter",
           "Requests by placement tier (ISSUE 10): packed = vmapped "
           "bucket lanes, mega = mesh-spanning sharded mega-lane.",
           [([("placement", p)], c)
            for p, c in sorted((s.get("placement") or {}).items())]
           or [([("placement", "packed")], 0)])
    metric("heat_tpu_serve_mega_lanes", "gauge",
           "Concurrent mega-lane slots (--mega-lanes; 0 = bucket "
           "overflow stays a rejection).",
           [([], s.get("mega_lanes", 0))])
    metric("heat_tpu_serve_mega_compiles_total", "counter",
           "Mega-lane programs compiled (chunk/seed/crop; warm "
           "re-admissions of the same oversized config compile nothing).",
           [([], s.get("mega_compiles", 0))])
    metric("heat_tpu_serve_queue_depth", "gauge",
           "Requests queued (not yet admitted to a lane), per tenant.",
           [([("tenant", t)], n)
            for t, n in sorted(engine.queue_depths().items())]
           or [([], 0)])
    for name, key, help_text in (
            ("heat_tpu_serve_shed_total", "shed",
             "Submits rejected by --max-queue / --tenant-quota."),
            ("heat_tpu_serve_deadline_misses_total", "deadline_misses",
             "Requests preempted or shed past their deadline_ms."),
            ("heat_tpu_serve_lanes_quarantined_total", "lanes_quarantined",
             "Requests failed nonfinite (lane quarantined)."),
            ("heat_tpu_serve_rollbacks_total", "rollbacks",
             "Per-lane restore-and-re-step events (--serve-on-nan rollback)."),
            ("heat_tpu_serve_watchdog_fired_total", "watchdog_fired",
             "Boundary-fetch watchdog timeouts."),
            ("heat_tpu_serve_lane_grows_total", "lane_grows",
             "Online lane-tier growth events (group rebuilt wider)."),
            ("heat_tpu_serve_chunks_dispatched_total", "chunks_dispatched",
             "Chunk programs dispatched across all bucket groups."),
            ("heat_tpu_serve_step_compiles_total", "step_compiles",
             "Steady stepping programs compiled (one per bucket x tier)."),
            ("heat_tpu_serve_boundary_waits_total", "boundary_waits",
             "Chunk-boundary fetches taken.")):
        metric(name, "counter", help_text, [([], s[key])])
    metric("heat_tpu_serve_boundary_wait_seconds_total", "counter",
           "Host wall seconds blocked on chunk-boundary fetches.",
           [([], s["boundary_wait_s"])])
    metric("heat_tpu_serve_resumed_requests_total", "counter",
           "Requests re-admitted from an engine-state checkpoint "
           "(serve --resume): in-flight lanes continued at their last "
           "boundary plus queued requests re-queued in policy order.",
           [([], s.get("serve_resumed", 0))])
    metric("heat_tpu_engine_ckpt_generation", "gauge",
           "Newest durable engine-checkpoint generation this process "
           "has published (0 = none yet; --engine-ckpt-interval).",
           [([], s.get("engine_ckpt_generation", 0))])
    metric("heat_tpu_flightrec_dumps_total", "counter",
           "Flight-recorder dumps written (watchdog fire / quarantine-"
           "after-rollbacks / numerics violation / scheduler crash); "
           "paths in the structured flightrec records and on /statusz.",
           [([], engine.tracer.dumps)])

    # --- numerics observatory (runtime/numerics.py, ISSUE 15) -------------
    metric("heat_tpu_numerics_enabled", "gauge",
           "1 while the numerics observatory ingests boundary stats "
           "(--numerics); the guard label names the violation routing.",
           [([("guard", s.get("numerics_guard", "warn"))],
             int(bool(s.get("numerics"))))])
    metric("heat_tpu_numerics_steady_total", "counter",
           "Requests whose residual EWMA converged below --steady-tol "
           "with steps still remaining (fire-once per request).",
           [([], s.get("steady_lanes", 0))])
    metric("heat_tpu_numerics_violations_total", "counter",
           "Maximum-principle escapes + heat-content jumps detected "
           "(one verdict per request; structured numerics_violation "
           "records carry the witnesses).",
           [([], s.get("numerics_violations", 0))])

    # --- semantic scheduling (ISSUE 16) -----------------------------------
    metric("heat_tpu_serve_steady_exits_total", "counter",
           "until=steady requests retired early at their dispatch "
           "frontier (residual EWMA passed tolerance before ntime).",
           [([], s.get("steady_exits", 0))])
    metric("heat_tpu_serve_steps_saved_total", "counter",
           "Device steps NOT run thanks to steady early exits (requested"
           " minus actual, summed over steady-exited requests).",
           [([], s.get("steps_saved", 0))])
    ns = (engine.numerics.snapshot()
          if engine.numerics is not None else None)
    metric("heat_tpu_numerics_predicted_eta_steps", "gauge",
           "Predicted steps until each resident lane's residual EWMA "
           "crosses its steady tolerance (fused eigenmode + observed "
           "slope, runtime/convergence.py); absent lanes have no "
           "prediction yet.",
           [([("id", rid)], st["eta_steps"])
            for rid, st in sorted((ns or {}).get("lanes", {}).items())
            if st.get("eta_steps") is not None] or [([], 0)])

    # --- canary prober (serve/probe.py) -----------------------------------
    pr = engine.prober.stats() if engine.prober is not None else None
    metric("heat_tpu_probe_runs_total", "counter",
           "Known-answer canary probes completed, by verdict (the sine-"
           "eigenmode request verified against its closed-form decay).",
           [([("result", "pass")], (pr or {}).get("passes", 0)),
            ([("result", "fail")], (pr or {}).get("fails", 0))])
    metric("heat_tpu_probe_consecutive_failures", "gauge",
           "Current run of back-to-back probe failures (a probe_failed "
           "record fires once the alert threshold is crossed).",
           [([], (pr or {}).get("consecutive_failures", 0))])
    metric("heat_tpu_probe_last_error_norm", "gauge",
           "Max-norm error of the last probe's returned field vs the "
           "analytic lambda**s decay (NaN until a probe completes).",
           [([], pr["last_error_norm"])]
           if pr and pr.get("last_error_norm") is not None else [([], 0)])
    metric("heat_tpu_probe_last_latency_seconds", "gauge",
           "End-to-end wall seconds of the last probe through the real "
           "gateway path.",
           [([], round(pr["last_latency_s"], 6))]
           if pr and pr.get("last_latency_s") is not None else [([], 0)])

    # --- performance & cost observatory (runtime/prof.py) ----------------
    cm = s.get("cost_model") or []
    metric("heat_tpu_serve_cost_s_per_lane_step", "gauge",
           "Online chunk-cost model: EWMA seconds per lane-step, per "
           "(bucket, lane-tier, dispatch-depth, kernel). The live "
           "counterpart of calibration_v5e.json (cross-check: heat-tpu "
           "perfcheck).",
           [([("bucket", e["bucket"]), ("lanes", e["lanes"]),
              ("depth", e["depth"]), ("kernel", e.get("kernel", "xla")),
              ("placement", e.get("placement", "packed"))],
             e["ewma_s_per_lane_step"])
            for e in cm if e["ewma_s_per_lane_step"] is not None]
           or [([], 0)])
    metric("heat_tpu_serve_cost_chunks_observed_total", "counter",
           "Chunk boundaries the cost model has learned from, per key.",
           [([("bucket", e["bucket"]), ("lanes", e["lanes"]),
              ("depth", e["depth"]), ("kernel", e.get("kernel", "xla")),
              ("placement", e.get("placement", "packed"))],
             e["chunks"]) for e in cm]
           or [([], 0)])
    metric("heat_tpu_serve_lane_kernel_fallbacks_total", "counter",
           "(bucket, lane-tier) groups that wanted the Pallas lane "
           "program and degraded to the XLA oracle (--serve-lane-kernel; "
           "structured lane_kernel_fallback records carry the reasons).",
           [([("requested", s.get("lane_kernel", "auto"))],
             s.get("lane_kernel_fallbacks", 0))])
    comp = prof_mod.compile_log().summary()
    metric("heat_tpu_compile_programs_total", "counter",
           "Chunk programs actually compiled by this process "
           "(aot_compile_chunks — solo solves and lane engines alike), "
           "by first-vs-warm key attribution.",
           [([("kind", "first")], comp["distinct"]),
            ([("kind", "warm")], comp["programs"] - comp["distinct"])])
    metric("heat_tpu_compile_seconds_total", "counter",
           "Wall seconds spent compiling chunk programs, by first-vs-"
           "warm (warm re-compile wall = persistent-cache report card).",
           [([("kind", "first")], comp["first_s"]),
            ([("kind", "warm")], comp["warm_s"])])
    mem = s.get("mem") or {}
    metric("heat_tpu_mem_bytes_in_use", "gauge",
           "Newest device-memory watermark sample (source label: "
           "allocator stats or live-array bytes).",
           [([("source", mem.get("source", "unavailable"))],
             mem.get("last_bytes") or 0)])
    metric("heat_tpu_mem_peak_bytes", "gauge",
           "Peak device-memory watermark this engine has seen.",
           [([], mem.get("peak_bytes") or 0)])
    metric("heat_tpu_mem_watermark_warnings_total", "counter",
           "Leak-sentinel firings (monotone growth past the byte floor).",
           [([], mem.get("warnings") or 0)])
    burn = s.get("slo_burn") or {}
    for name, field, help_text in (
            ("heat_tpu_slo_burn_rate", None,
             "Error-budget burn rate per class and window (1.0 = burning "
             "exactly at the sustainable rate; >threshold in both windows "
             "emits a structured slo_alert)."),
            ("heat_tpu_slo_deadline_hit_ratio", "hit",
             "Deadline-hit fraction per class and window (dated requests "
             "only; absent window = no dated traffic).")):
        samples = []
        for cls, b in sorted(burn.items()):
            for window in ("fast", "slow"):
                v = (b[f"{window}_burn"] if field is None
                     else b[f"{window}_hit_ratio"])
                if v is not None:
                    samples.append(
                        ([("class", cls), ("window", window)], v))
        metric(name, "gauge", help_text, samples or [([], 0)])
    metric("heat_tpu_slo_alerts_total", "counter",
           "Structured slo_alert records emitted, per class.",
           [([("class", cls)], b["alerts"])
            for cls, b in sorted(burn.items())] or [([], 0)])
    cache = s.get("cache") or {}
    metric("heat_tpu_cache_hits_total", "counter",
           "Solve-cache hits by kind: 'full' short-circuits admission "
           "(served byte-identically from disk, no lane), 'prefix' "
           "seeds a lane from a cached frontier and steps the delta.",
           [([("kind", "full")], cache.get("hits_full", 0)),
            ([("kind", "prefix")], cache.get("hits_prefix", 0))])
    metric("heat_tpu_cache_misses_total", "counter",
           "Solve-cache consults that found no usable entry.",
           [([], cache.get("misses", 0))])
    metric("heat_tpu_cache_evictions_total", "counter",
           "Entries LRU-evicted to honor --cache-max-bytes.",
           [([], cache.get("evictions", 0))])
    metric("heat_tpu_cache_quarantined_total", "counter",
           "Entries that failed validation on consult and were renamed "
           "to *.corrupt (cache_quarantined records carry the reason).",
           [([], cache.get("quarantined", 0))])
    metric("heat_tpu_cache_entries", "gauge",
           "Published cache entries on disk right now.",
           [([], cache.get("entries", 0))])
    metric("heat_tpu_cache_bytes", "gauge",
           "Bytes the cache directory holds right now.",
           [([], cache.get("bytes", 0))])
    usage = engine.prof.ledger.snapshot()
    for name, field, help_text in (
            ("heat_tpu_usage_lane_seconds_total", "lane_s",
             "Lane-occupancy seconds consumed, per tenant and class "
             "(the per-request usage stamps, aggregated)."),
            ("heat_tpu_usage_steps_total", "steps",
             "Simulation steps served, per tenant and class."),
            ("heat_tpu_usage_chunks_total", "chunks",
             "Chunk programs participated in, per tenant and class."),
            ("heat_tpu_usage_bytes_written_total", "bytes_written",
             "Result bytes produced, per tenant and class."),
            ("heat_tpu_usage_steps_saved_total", "steps_saved",
             "Steps not run thanks to until=steady early exits and "
             "solve-cache hits, per tenant and class (saved device time "
             "billed as saved)."),
            ("heat_tpu_usage_cached_total", "cached",
             "Requests served entirely from the solve cache (zero "
             "lane-seconds/steps billed), per tenant and class."),
            ("heat_tpu_usage_requests_total", "requests",
             "Terminal requests accounted, per tenant and class.")):
        metric(name, "counter", help_text,
               [([("tenant", tenant), ("class", cls)], c[field])
                for tenant, t in sorted(usage["tenants"].items())
                for cls, c in sorted(t["classes"].items())]
               or [([], 0)])

    def histogram(name, help_text, label, hist):
        out.append(f"# HELP {name} {help_text}")
        out.append(f"# TYPE {name} histogram")
        snap = hist.snapshot()
        lbl = (f'{label[0]}="{escape_label_value(label[1])}",'
               if label else "")
        for le, cum in snap["buckets"]:
            out.append(f'{name}_bucket{{{lbl}le="{le}"}} {cum}')
        suffix = "{" + lbl.rstrip(",") + "}" if label else ""
        out.append(f"{name}_sum{suffix} {snap['sum']:.6f}")
        out.append(f"{name}_count{suffix} {snap['count']}")

    for cls in sorted(engine.lat_hist):
        histogram("heat_tpu_serve_request_latency_seconds",
                  "End-to-end request latency (submit to terminal record), "
                  "per SLO class.", ("class", cls), engine.lat_hist[cls])
    histogram("heat_tpu_serve_queue_depth_observed",
              "Total queue depth observed at each accepted submit.",
              None, engine.depth_hist)
    return "\n".join(out) + "\n"


def usage_payload(engine: Engine) -> dict:
    """The ``GET /v1/usage`` body: the per-tenant usage ledger
    (runtime/prof.py) plus identity fields. Pure function of the engine
    so the exact-reconciliation test asserts on it without a socket.
    ``totals`` sums the same stamps every terminal record carries — the
    two views reconcile exactly by construction."""
    payload = engine.prof.ledger.snapshot()
    payload["prof"] = engine.scfg.prof
    payload["uptime_s"] = round(trace_mod.process_uptime_s(), 3)
    return payload


def status_payload(engine: Engine) -> dict:
    """The ``GET /v1/status`` body: the machine-readable twin of
    ``/statusz``, shaped for a fleet router's placement policy
    (heat_tpu/fleet/placement.py) — per-tenant queue depths, backlog
    step sums, the online cost-model rows (so the router can convert
    queue work into predicted backlog seconds), SLO burn gauges (the
    burn-aware demotion signal), mega capability (oversized-request
    routing), checkpoint generation (the steal handshake), and the
    prober counters the health checker folds in. Pure function of the
    engine so placement tests can assert on it without a socket; the
    handler adds the gateway-scoped fields (address, drained)."""
    s = engine.summary()
    pr = engine.prober.stats() if engine.prober is not None else None
    mega_lanes = int(s.get("mega_lanes", 0) or 0)
    return {
        "kind": "heat-tpu-engine-status",
        "uptime_s": round(trace_mod.process_uptime_s(), 3),
        "online": bool(engine.online),
        "draining": bool(engine.draining),
        "loop_error": (f"{type(engine.loop_error).__name__}: "
                       f"{engine.loop_error}"
                       if engine.loop_error is not None else None),
        "policy": s["policy"],
        "dispatch_depth": s["dispatch_depth"],
        "requests": {st: s.get(st, 0)
                     for st in (*TERMINAL_STATUSES, "queued", "running")},
        "queued_now": s.get("queued_now", 0),
        "queue_depths": engine.queue_depths(),
        "backlog": engine.backlog_snapshot(),
        "cost_model": s.get("cost_model") or [],
        "slo_burn": s.get("slo_burn") or {},
        "shed": s.get("shed", 0),
        "watchdog_fired": s.get("watchdog_fired", 0),
        "mega": {"lanes": mega_lanes,
                 "capable": mega_lanes > 0,
                 "buckets": [int(b) for b in engine.scfg.buckets],
                 "max_bucket": max((int(b) for b in engine.scfg.buckets),
                                   default=0)},
        "engine_ckpt": {"generation": s.get("engine_ckpt_generation", 0),
                        "interval": s.get("engine_ckpt_interval", 0),
                        "dir": engine.engine_ckpt_dir()},
        "cache": s.get("cache"),
        "serve_resumed": s.get("serve_resumed", 0),
        "probe": pr,
        "flightrec_dumps": engine.tracer.dumps,
    }


def render_statusz(engine: Engine) -> str:
    """The ``GET /statusz`` page: one human-readable snapshot of the
    serving process for an operator mid-incident — counters, the online
    cost model, compile observatory, memory watermarks, SLO burn, top
    tenants, flight-recorder dumps. Text on purpose: curl-able from any
    box with no dashboard in reach."""
    s = engine.summary()
    lines = [f"heat-tpu serving engine — statusz "
             f"(uptime {trace_mod.process_uptime_s():.0f}s, "
             f"policy {s['policy']}, dispatch depth {s['dispatch_depth']}, "
             f"observatory {'on' if s['prof'] else 'OFF'})", ""]
    lines.append(
        f"requests: {s['requests']} total — "
        + ", ".join(f"{s.get(st, 0)} {st}" for st in
                    (*TERMINAL_STATUSES, "queued", "running")
                    if s.get(st)))
    pl = s.get("placement") or {}
    lines.append(
        f"placement: {pl.get('packed', 0)} packed / "
        f"{pl.get('mega', 0)} mega — {s.get('mega_lanes', 0)} mega "
        f"lane slot(s) (--mega-lanes; bucket-overflow requests run on "
        f"the mesh), {s.get('mega_compiles', 0)} mega compile(s)")
    lines.append(
        f"engine: {s['chunks_dispatched']} chunk(s) "
        f"({s['tail_chunks']} tail), {s['boundary_waits']} boundary "
        f"wait(s) {s['boundary_wait_s']:.3f}s, device idle "
        f"{s['device_idle_s']:.3f}s, {s['step_compiles']}+"
        f"{s['tail_compiles']} compiles {s['compile_s']:.2f}s, "
        f"{s['lane_grows']} lane grow(s), lane kernel "
        f"{s.get('lane_kernel', 'auto')} "
        f"({s.get('lane_kernel_fallbacks', 0)} fallback(s))")
    lines.append(
        f"faults: {s['lanes_quarantined']} quarantined, "
        f"{s['rollbacks']} rollback(s), {s['deadline_misses']} deadline "
        f"miss(es), {s['shed']} shed, {s['watchdog_fired']} watchdog")
    iv = s.get("engine_ckpt_interval", 0)
    lines.append(
        f"resume: engine checkpoint "
        f"{f'every {iv} boundaries' if iv else 'OFF (--engine-ckpt-interval 0)'}"
        f", last published generation {s.get('engine_ckpt_generation', 0)}, "
        f"{s.get('serve_resumed', 0)} request(s) re-admitted from a "
        f"checkpoint this incarnation")
    cache = s.get("cache")
    if cache is None:
        lines.append("solve cache: OFF (--cache off)")
    else:
        lines.append(
            f"solve cache: {cache['hits_full']} full / "
            f"{cache['hits_prefix']} prefix hit(s), "
            f"{cache['misses']} miss(es) of {cache['consults']} "
            f"consult(s), {cache['entries']} entr(ies) / "
            f"{cache['bytes'] / 2**20:.2f} MiB on disk "
            f"(budget {cache['max_bytes'] or 'unbounded'}, "
            f"{cache['evictions']} evicted, "
            f"{cache['quarantined']} quarantined) — {cache['dir']}")
    if s.get("numerics"):
        lines.append(
            f"numerics: guard {s.get('numerics_guard', 'warn')}, "
            f"{s.get('steady_lanes', 0)} steady lane(s), "
            f"{s.get('numerics_violations', 0)} violation(s); semantic "
            f"scheduling: {s.get('steady_exits', 0)} steady exit(s), "
            f"{s.get('steps_saved', 0)} step(s) saved")
        ns = engine.numerics.snapshot() if engine.numerics else None
        for rid, ln in sorted((ns or {}).get("lanes", {}).items()):
            if ln["resid_ewma"] is None:
                continue
            eta = ln.get("eta_steps")
            lines.append(
                f"  {rid}: resid ewma {ln['resid_ewma']:.3e}, heat "
                f"{ln['heat']:.6g}, range [{ln['tmin']:.4g}, "
                f"{ln['tmax']:.4g}] in [{ln['lo']:g}, {ln['hi']:g}]"
                f"{f', eta ~{eta} step(s)' if eta is not None else ''}"
                f"{' STEADY' if ln['steady'] else ''}"
                f"{' VIOLATED' if ln['violated'] else ''}")
    else:
        lines.append("numerics: observatory OFF (--numerics off)")
    pr = engine.prober.stats() if engine.prober is not None else None
    if pr is None:
        lines.append("prober: not armed (--probe-interval 0)")
    else:
        en = pr.get("last_error_norm")
        lines.append(
            f"prober: every {pr['interval_s']:g}s, {pr['passes']} pass / "
            f"{pr['fails']} fail ({pr['consecutive_failures']} "
            f"consecutive), last error norm "
            f"{'n/a' if en is None else format(en, '.3e')}, last latency "
            f"{pr.get('last_latency_s') or 0:.3f}s")
    cm = s.get("cost_model") or []
    lines.append("")
    lines.append(f"cost model ({len(cm)} key(s), s/lane-step EWMA; "
                 f"cross-check: heat-tpu perfcheck):")
    if not cm:
        lines.append("  (no chunk boundaries observed yet)")
    for e in cm:
        ew = e["ewma_s_per_lane_step"]
        lines.append(
            f"  {e['bucket']} xL{e['lanes']} depth{e['depth']} "
            f"[{e.get('kernel', 'xla')}/{e.get('placement', 'packed')}]: "
            f"{'n/a' if ew is None else format(ew, '.3e')} s/lane-step "
            f"(p95 {e['p95_s_per_lane_step'] or 0:.0e}, "
            f"{e['chunks']} chunk(s), {e['wall_s']:.3f}s observed)")
    comp = s.get("compile", prof_mod.compile_log().summary())
    lines.append("")
    lines.append(
        f"compile observatory (process-wide): {comp['programs']} "
        f"program(s) / {comp['distinct']} distinct key(s), "
        f"{comp['total_s']:.2f}s total ({comp['first_s']:.2f}s first-time, "
        f"{comp['warm_s']:.2f}s warm re-compiles)")
    mem = s.get("mem") or {}
    lines.append(
        f"memory watermarks: peak "
        f"{(mem.get('peak_bytes') or 0) / 2**20:.1f} MiB, last "
        f"{(mem.get('last_bytes') or 0) / 2**20:.1f} MiB "
        f"({mem.get('source', 'unavailable')}; {mem.get('samples', 0)} "
        f"sample(s), {mem.get('warnings', 0)} leak warning(s))")
    burn = s.get("slo_burn") or {}
    lines.append("")
    lines.append("slo burn (dated requests; budget = 1 - target):")
    if not burn:
        lines.append("  (no dated traffic yet)")
    for cls, b in sorted(burn.items()):
        lines.append(
            f"  {cls}: target {b['target']:g}, burn fast "
            f"{b['fast_burn']:.2f}x / slow {b['slow_burn']:.2f}x, "
            f"hit fast {b['fast_hit_ratio']} / slow {b['slow_hit_ratio']} "
            f"({b['fast_events']}/{b['slow_events']} events, "
            f"{b['alerts']} alert(s))")
    usage = engine.prof.ledger.snapshot()
    tot = usage["totals"]
    lines.append("")
    lines.append(
        f"usage ledger: {tot['requests']} request(s), "
        f"{tot['lane_s']:.3f} lane-s, {tot['steps']} steps, "
        f"{tot.get('cached', 0)} cached, {tot['chunks']} chunk-slots, "
        f"{tot['bytes_written'] / 2**20:.2f} MiB written "
        f"(full detail: GET /v1/usage or heat-tpu usage URL)")
    top = sorted(usage["tenants"].items(),
                 key=lambda kv: -kv[1]["lane_s"])[:5]
    for tenant, t in top:
        lines.append(
            f"  {tenant}: {t['lane_s']:.3f} lane-s, {t['steps']} steps "
            f"({t.get('steps_saved', 0)} saved, "
            f"{t.get('cached', 0)} cached), "
            f"{t['requests']} request(s), "
            f"{t['bytes_written'] / 2**20:.2f} MiB")
    if engine.tracer.dumps:
        lines.append("")
        lines.append(f"flight-recorder dumps ({engine.tracer.dumps}):")
        for p in engine.tracer.dump_paths:
            lines.append(f"  {p}")
    return "\n".join(lines) + "\n"


class Gateway:
    """The long-running front-end over one online :class:`Engine`.

    >>> gw = Gateway(Engine(scfg), "127.0.0.1", 0).start()
    >>> gw.address            # actual host:port (port 0 = ephemeral)
    >>> gw.request_drain()    # or POST /drainz
    >>> gw.wait_drained(30)
    >>> gw.close()
    """

    def __init__(self, engine: Engine, host: str = "127.0.0.1",
                 port: int = 0, retry_after_s: float = 1.0,
                 stream_timeout_s: float = 600.0,
                 start_engine: bool = True, quiet: bool = True):
        self.engine = engine
        self.retry_after_s = retry_after_s
        self.stream_timeout_s = stream_timeout_s
        self._start_engine = start_engine
        self.quiet = quiet
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.daemon_threads = True   # a wedged client cannot hold
                                           # process exit hostage
        self.httpd.gateway = self          # handler back-pointer
        self.host, self.port = self.httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None
        self._drainer: Optional[threading.Thread] = None
        self._drain_lock = debug.make_lock("gateway:drain")
        self._drained = threading.Event()
        # race sanitizer (no-op unless HEAT_TPU_RACECHECK): engine and
        # httpd are object references on every handler's path — their
        # own fields are watched by their own instrumentation
        debug.instrument_races(
            self, label="Gateway",
            exempt=frozenset({"engine", "httpd"}))

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "Gateway":
        if self._start_engine:
            self.engine.start()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True,
                                        name="heat-tpu-gateway-http")
        self._thread.start()
        return self

    # --- drain ------------------------------------------------------------
    def request_drain(self, handoff: bool = False) -> bool:
        """Begin the graceful drain (idempotent): admission stops now,
        in-flight lanes and already-queued requests finish, then the
        scheduler exits. Returns True once fully drained.

        ``handoff=True`` (POST /drainz?handoff=1) is drain-to-checkpoint:
        instead of waiting for lanes to finish, the scheduler checkpoints
        the whole engine at the next empty-pipeline boundary and exits —
        a replacement process picks the work up with ``serve --resume``.
        Handoff wins over a concurrent plain drain (escalation is safe;
        de-escalation would strand in-flight work unfinished AND
        uncheckpointed)."""
        self.engine.begin_drain(handoff=handoff)
        with self._drain_lock:
            if self._drainer is None:
                self._drainer = threading.Thread(target=self._drain_worker,
                                                 daemon=True,
                                                 name="heat-tpu-gateway-drain")
                self._drainer.start()
        return self._drained.is_set()

    def _drain_worker(self) -> None:
        self.engine.shutdown()
        self._drained.set()

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        return self._drained.wait(timeout)

    def close(self) -> None:
        """Tear the HTTP listener down (does NOT drain the engine — call
        request_drain/wait_drained first for a graceful exit)."""
        self.httpd.shutdown()
        self.httpd.server_close()


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.1 for chunked transfer encoding (the streaming response)
    protocol_version = "HTTP/1.1"

    @property
    def gw(self) -> Gateway:
        return self.server.gateway

    # --- plumbing ---------------------------------------------------------
    def log_message(self, fmt, *args):  # noqa: D102 — per-request stderr
        if not self.gw.quiet:           # lines would swamp serve output
            master_print(f"gateway: {self.address_string()} {fmt % args}")

    @property
    def trace_id(self) -> str:
        """The X-Trace-Id EVERY response to this request echoes: the
        client's inbound header when sane (so a client-side id survives
        the round trip even on a 4xx/5xx), else a freshly minted id.
        Cached per request; /v1/solve overrides it with the per-request
        ids it mints."""
        tid = getattr(self, "_trace_id", None)
        if tid is None:
            inbound = (self.headers.get("X-Trace-Id") or "").strip()
            tid = (inbound if _TRACE_ID_RE.match(inbound)
                   else self.gw.engine.tracer.mint_trace_id())
            self._trace_id = tid
        return tid

    def _send_headers(self, code: int, body_len: int, ctype: str,
                      headers=()) -> None:
        """Shared response-header path: the one place that guarantees the
        X-Trace-Id contract (satellite audit, ISSUE 8) — an explicit
        X-Trace-Id in ``headers`` wins; every other response gets the
        request-scoped default, 429s and 400s and /drainz included."""
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(body_len))
        has_tid = False
        for k, v in headers:
            self.send_header(k, str(v))
            has_tid = has_tid or k == "X-Trace-Id"
        if not has_tid:
            self.send_header("X-Trace-Id", self.trace_id)
        self.end_headers()

    def _json(self, code: int, obj, headers=()) -> None:
        body = (json.dumps(obj, sort_keys=True) + "\n").encode()
        self._send_headers(code, len(body), "application/json", headers)
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _text(self, code: int, text: str, ctype: str) -> None:
        body = text.encode()
        self._send_headers(code, len(body), ctype)
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    @staticmethod
    def _sanitize(rec: dict) -> dict:
        return {k: v for k, v in rec.items() if k != "T"}

    # --- routes -----------------------------------------------------------
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        parts = urlsplit(self.path)
        path = parts.path
        eng = self.gw.engine
        if path == "/healthz":
            if eng.loop_error is not None:
                self._json(500, {"status": "error",
                                 "error": f"{type(eng.loop_error).__name__}: "
                                          f"{eng.loop_error}"})
            elif eng.draining:
                self._json(503, {"status": "draining",
                                 "drained": self.gw.wait_drained(0)},
                           headers=[("Retry-After",
                                     int(self.gw.retry_after_s))])
            else:
                self._json(200, {"status": "ok", "online": eng.online})
        elif path == "/metrics":
            self._text(200, render_metrics(eng),
                       "text/plain; version=0.0.4")
        elif path == "/statusz":
            self._text(200, render_statusz(eng), "text/plain; charset=utf-8")
        elif path == "/v1/usage":
            self._json(200, usage_payload(eng))
        elif path == "/v1/status":
            payload = status_payload(eng)
            payload["address"] = self.gw.address
            payload["drained"] = self.gw.wait_drained(0)
            self._json(200, payload)
        elif path == "/tracez":
            # the flight recorder's ring, on demand: a Chrome trace JSON
            # snapshot of the engine as it runs (loadable in Perfetto —
            # no fault required, no drain required)
            self._text(200, json.dumps(eng.tracer.to_chrome()),
                       "application/json")
        elif path == "/drainz":
            self._drainz(parts)
        elif path.startswith("/v1/requests/"):
            rid = path[len("/v1/requests/"):]
            rec = eng.poll(rid)
            if rec is None:
                self._json(404, {"error": f"unknown request id {rid!r}"})
            else:
                body = self._sanitize(rec)
                if parse_qs(parts.query).get("field", ["0"])[0] in ("1",
                                                                    "true"):
                    # ?field=1: inline the final field as nested JSON
                    # lists (f64 — bfloat16 is not JSON-spellable). The
                    # canary prober verifies returned solutions through
                    # this, the same front door every client uses.
                    T = eng.field_of(rid)
                    if T is not None:
                        import numpy as np

                        body["T"] = np.asarray(
                            T, dtype=np.float64).tolist()
                self._json(200, body,
                           headers=[("X-Trace-Id", rec["trace_id"])]
                           if rec.get("trace_id") else ())
        else:
            self._json(404, {"error": f"no route for GET {path}"})

    def do_POST(self):  # noqa: N802
        parts = urlsplit(self.path)
        if parts.path == "/drainz":
            self._drainz(parts)
        elif parts.path == "/v1/solve":
            self._solve(parts)
        elif parts.path == "/v1/resume":
            self._resume()
        elif parts.path == "/v1/cancel":
            self._cancel()
        else:
            self._json(404, {"error": f"no route for POST {parts.path}"})

    def _drainz(self, parts=None) -> None:
        """Idempotent graceful drain trigger (POST preferred; GET kept
        for curl ergonomics). ``?handoff=1`` checkpoints the engine at
        the next empty-pipeline boundary instead of finishing lanes —
        the zero-downtime handoff contract (see Gateway.request_drain)."""
        handoff = (parts is not None
                   and parse_qs(parts.query).get("handoff", ["0"])[0]
                   in ("1", "true"))
        drained = self.gw.request_drain(handoff=handoff)
        eng = self.gw.engine
        self._json(200, {"draining": True, "drained": drained,
                         "handoff": handoff,
                         "queued": sum(eng.queue_depths().values())})

    def _resume(self) -> None:
        """``POST /v1/resume`` body ``{"dir": PATH}``: re-admit the work
        a sibling engine checkpointed under ``PATH`` into THIS (live)
        engine through ``resume_engine``'s skip-set front door — the
        receiving half of the fleet router's checkpoint-handoff work
        steal (`/drainz?handoff=1` on the victim is the sending half).
        Returns the manifest generation plus the recovered/done id
        lists so the router knows exactly which orphans to poll here
        and which to re-drive fresh."""
        from . import resume as resume_mod

        eng = self.gw.engine
        if eng.draining:
            self._json(503, {"error": "draining: this backend cannot "
                                      "adopt work (/drainz)"},
                       headers=[("Retry-After",
                                 int(self.gw.retry_after_s))])
            return
        body = self._read_body()
        if body is None:
            return
        try:
            obj = json.loads(body.decode("utf-8", "replace") or "{}")
            resume_dir = obj["dir"]
        except (ValueError, KeyError, TypeError):
            self._json(400, {"error": "expected a JSON body "
                                      "{\"dir\": PATH}"})
            return
        try:
            # skip_known: the router's re-drive can race the manifest —
            # ids this engine already holds are skipped, not a conflict
            detail = resume_mod.resume_engine_detail(eng, resume_dir,
                                                     skip_known=True)
        except ValueError as e:
            # fingerprint mismatch: the manifest does not belong on
            # this backend — a structured conflict, not a 500
            self._json(409, {"error": str(e)})
            return
        self._json(200, detail)

    def _cancel(self) -> None:
        """``POST /v1/cancel`` body ``{"id": RID}``: deadline-preempt a
        queued or running request at its next chunk boundary (the fleet
        router's hedged-dispatch loser cancel; see Engine.cancel).
        ``{"cancelled": false}`` for unknown/terminal ids — cancelling
        finished work is a no-op, not an error."""
        body = self._read_body()
        if body is None:
            return
        try:
            rid = json.loads(body.decode("utf-8", "replace") or "{}")["id"]
        except (ValueError, KeyError, TypeError):
            self._json(400, {"error": "expected a JSON body "
                                      "{\"id\": REQUEST_ID}"})
            return
        self._json(200, {"id": rid,
                         "cancelled": self.gw.engine.cancel(str(rid))})

    # --- /v1/solve --------------------------------------------------------
    def _read_body(self) -> Optional[bytes]:
        n = self.headers.get("Content-Length")
        if n is None:
            self._json(411, {"error": "Content-Length required"})
            return None
        n = int(n)
        if n > MAX_BODY_BYTES:
            self._json(413, {"error": f"body exceeds {MAX_BODY_BYTES} "
                                      f"bytes"})
            return None
        return self.rfile.read(n)

    def _solve(self, parts) -> None:
        """One HTTP receive/parse/submit/stream span on the gateway
        handler thread's track — the front half of every request's flow
        (Engine.submit anchors the flow start on this same thread)."""
        tr = self.gw.engine.tracer
        if not tr.enabled:
            return self._solve_inner(parts)
        t0 = tr.now()
        try:
            self._solve_inner(parts)
        finally:
            tr.complete("POST /v1/solve", tr.thread_track("gateway"), t0,
                        cat="http")

    def _solve_inner(self, parts) -> None:
        gw, eng = self.gw, self.gw.engine
        if eng.draining:
            self._json(503, {"error": "draining: admission stopped "
                                      "(/drainz); retry against another "
                                      "replica"},
                       headers=[("Retry-After", int(gw.retry_after_s))])
            return
        # cross-host deadline propagation: the fleet edge mints the
        # budget and decrements it per hop/retry — if it arrives here
        # already spent, refuse to admit rather than start expired work
        # (the row would only be shed at the first chunk boundary after
        # burning device steps the tenant is never billed for).
        hdr = self.headers.get("X-Deadline-Ms")
        if hdr is not None:
            try:
                remaining_ms = float(hdr)
            except ValueError:
                self._json(400, {"error": f"bad X-Deadline-Ms {hdr!r}: "
                                          "expected milliseconds"})
                return
            if remaining_ms <= 0:
                self._json(504, {"error": "deadline: edge-minted budget "
                                          "exhausted before this hop; "
                                          "batch never admitted"})
                return
        body = self._read_body()
        if body is None:
            return
        wait = parse_qs(parts.query).get("wait", ["1"])[0] not in ("0",
                                                                   "false")
        # streaming responses need the listener registered BEFORE any
        # submit: a tiny request could otherwise finish in the gap
        results: queue_lib.Queue = queue_lib.Queue()
        listener = results.put
        if wait:
            eng.add_listener(listener)
        try:
            immediate, submitted = [], []
            for line in body.decode("utf-8", "replace").splitlines():
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                try:
                    row = parse_request_obj(json.loads(line))
                except Exception as e:  # noqa: BLE001 — per-line record
                    immediate.append({"id": None, "status": "rejected",
                                      "error": f"{type(e).__name__}: {e}"})
                    continue
                if row.error is not None:
                    immediate.append({"id": row.id, "status": "rejected",
                                      "error": row.error})
                    continue
                try:
                    submitted.append(submit_parsed(eng, row))
                except ValueError as e:   # duplicate id etc.
                    immediate.append({"id": row.id, "status": "rejected",
                                      "error": str(e)})
            if not immediate and not submitted:
                self._json(400, {"error": "empty body: expected one JSON "
                                          "request object per line"})
                return
            # backpressure: every submitted request shed at admission ->
            # 429 so well-behaved clients back off (Retry-After)
            snaps = {rid: eng.poll(rid) for rid in submitted}
            # every response names the request-scoped trace ids it minted
            # (one per submitted line, comma-joined) so a client log line
            # can be joined against /tracez and flight-recorder dumps
            tids = ",".join(str(r.get("trace_id"))
                            for r in snaps.values() if r.get("trace_id"))
            tid_hdr = [("X-Trace-Id", tids)] if tids else []
            overloaded = [rid for rid, r in snaps.items()
                          if r["status"] == "rejected"
                          and str(r.get("error", "")).startswith(
                              _OVERLOAD_PREFIX)]
            if submitted and len(overloaded) == len(submitted):
                eng_shed = [self._sanitize(snaps[rid]) for rid in submitted]
                body_out = {"error": "overloaded: admission queue full; "
                                     "retry after the indicated delay",
                            "records": immediate + eng_shed}
                self._json(429, body_out,
                           headers=[("Retry-After", int(gw.retry_after_s)),
                                    *tid_hdr])
                return
            if not wait:
                self._json(202, {"accepted": submitted,
                                 "records": immediate},
                           headers=tid_hdr)
                return
            self._stream(immediate, submitted, snaps, results,
                         headers=tid_hdr)
        finally:
            if wait:
                eng.remove_listener(listener)

    def _stream(self, immediate, submitted, snaps, results,
                headers=()) -> None:
        """Chunked NDJSON: parse-failure records first, then one record
        per submitted request in FINISH order, each written the moment
        its terminal record lands (listener-fed queue). Bounded by the
        gateway's stream timeout so a wedged engine cannot hold the
        socket forever."""
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        has_tid = False
        for k, v in headers:
            self.send_header(k, str(v))
            has_tid = has_tid or k == "X-Trace-Id"
        if not has_tid:
            self.send_header("X-Trace-Id", self.trace_id)
        self.end_headers()

        def chunk(obj) -> bool:
            data = (json.dumps(obj, sort_keys=True, default=str)
                    + "\n").encode()
            try:
                self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
                return True
            except (BrokenPipeError, ConnectionResetError):
                return False   # client went away: stop relaying (the
                               # engine still finishes the requests)
        alive = True
        for rec in immediate:
            alive = alive and chunk(rec)
        pending = set(submitted)
        # records already terminal before the listener registered (the
        # submit itself rejected, or a racing tiny request)
        for rid in submitted:
            rec = snaps[rid]
            if rec["status"] in TERMINAL_STATUSES and rid in pending:
                pending.discard(rid)
                alive = alive and chunk(self._sanitize(rec))
        deadline = _monotonic() + self.gw.stream_timeout_s
        while pending and alive:
            try:
                rec = results.get(timeout=max(0.05,
                                              deadline - _monotonic()))
            except queue_lib.Empty:
                chunk({"error": f"stream timeout after "
                                f"{self.gw.stream_timeout_s:g}s; poll "
                                f"GET /v1/requests/<id> for the rest",
                       "pending": sorted(pending)})
                break
            rid = rec.get("id")
            if rid in pending:
                pending.discard(rid)
                alive = alive and chunk(self._sanitize(rec))
        try:
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass


def _monotonic() -> float:
    import time

    return time.monotonic()
