"""Multi-tenant serving engine: continuous batching over vmapped lanes.

The reference runs exactly one solve per process invocation (``program
heat`` reads one ``input.dat`` and exits); the ROADMAP north star is a
system serving *many* independent solve requests as batched device work.
This package applies the continuous-batching shape of modern inference
servers (Orca-style iteration-level scheduling — see PAPERS.md) to the
paper's FTCS stencil:

- ``engine.py``    — the device half: up to L same-bucket grids stacked
  into one ``(L, ny, nx)`` array with per-lane scalar params and an
  active-lane mask, all lanes stepped by one jitted shape-stable chunk
  program (masked lanes step too; their results are ignored).
- ``scheduler.py`` — the host half: admission queue, shape bucketing
  (requests padded up to a small set of grid buckets so there is at most
  one stepping-program compile per bucket x lane-tier), and
  *dispatch-ahead* continuous batching — a configurable depth of chunk
  programs stays in flight per group while the scheduler inspects the
  oldest boundary, finished lanes hand a one-lane device snapshot to the
  async writeback pipeline without stopping the stepping, and chunk
  dispatch round-robins across bucket groups so one group's bookkeeping
  hides under another's compute.
- ``api.py``       — the request JSONL contract and the ``heat-tpu
  serve`` entry point.
- ``policy.py``    — pluggable admission ordering (fifo | edf | fair):
  per-tenant SLO classes, weighted fair share, deadline-aware admission.
- ``gateway.py``   — the online HTTP front-end (``serve --listen``):
  streaming admission into a running engine, 429/Retry-After
  backpressure, graceful drain, and the /metrics surface.
"""

from .api import (ParsedRequest, load_requests,  # noqa: F401
                  parse_request_obj, serve_requests, submit_parsed)
from .engine import (BucketKey, LaneEngine, lane_buffer,  # noqa: F401
                     lane_tier, tail_size)
from .resume import resume_engine  # noqa: F401
from .scheduler import (TERMINAL_STATUSES, Engine,  # noqa: F401
                        Request, ServeConfig)
from .solvecache import SolveCache  # noqa: F401


def __getattr__(name):
    # Gateway imports lazily: the offline drain must not pay for (or
    # depend on) the HTTP stack it never uses.
    if name in ("Gateway", "render_metrics", "render_statusz",
                "usage_payload"):
        from . import gateway

        return getattr(gateway, name)
    raise AttributeError(name)
