"""Serial numpy backend — the correctness oracle.

A faithful, dependency-light reimplementation of the reference's serial
solvers (``fortran/serial/heat.f90:61-69``, ``python/serial/heat.py:48-58``):
host-only, per-step full-array snapshot, vectorized slice stencil. Every
other backend is tested against this one (the test pyramid the reference
lacks, SURVEY.md §4).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..config import HeatConfig
from ..grid import np_dtype
from ..runtime import checkpoint, debug, faults
from ..runtime.logging import master_print
from ..runtime.timing import Timing
from . import SolveResult, register


def _lap_interior(T: np.ndarray) -> np.ndarray:
    # summation order = the reference expression left-to-right (+1 neighbors
    # in axis order, then -1 neighbors, then -2*nd*center — fortran/serial/
    # heat.f90:64-68), so f64 runs bit-match the reference on any field
    nd = T.ndim
    ctr = tuple(slice(1, -1) for _ in range(nd))
    shifted = []
    for off in (slice(2, None), slice(0, -2)):
        for d in range(nd):
            sl = list(ctr)
            sl[d] = off
            shifted.append(T[tuple(sl)])
    acc = shifted[0]
    for s in shifted[1:]:
        acc = acc + s
    return acc + (-2.0 * nd) * T[ctr]


def step_edges_np(T: np.ndarray, r: float) -> np.ndarray:
    """Frozen-boundary step (serial loop bounds 2..n-1, heat.f90:64-68)."""
    ctr = tuple(slice(1, -1) for _ in range(T.ndim))
    out = T.copy()
    out[ctr] = T[ctr] + r * _lap_interior(T)
    return out


def step_ghost_np(T: np.ndarray, r: float, bc_value: float) -> np.ndarray:
    """Dirichlet-by-ghost step: all cells update against a bc_value ring
    (the undecomposed equivalent of fortran/mpi+cuda/heat.F90:206-219)."""
    padded = np.pad(T, 1, mode="constant", constant_values=bc_value)
    return T + r * _lap_interior(padded)


def step_periodic_np(T: np.ndarray, r: float) -> np.ndarray:
    """Torus step: wrap-pad supplies the opposite-edge neighbors — the
    ``pbc=.true.`` topology the reference's cartesian communicator carries
    but never enables (fortran/mpi+cuda/heat.F90:76,97)."""
    padded = np.pad(T, 1, mode="wrap")
    return T + r * _lap_interior(padded)


@register("serial")
def solve(cfg: HeatConfig, T0: Optional[np.ndarray] = None, **_) -> SolveResult:
    from .common import load_or_init

    t_all0 = time.perf_counter()
    dt = np_dtype(cfg.dtype)
    T0_host, start_step = load_or_init(cfg, T0)
    T = np.array(T0_host, dtype=dt)
    r = dt(cfg.r)

    plan = faults.plan_for(cfg)  # None in every normal run (strictly opt-in)
    t0 = time.perf_counter()
    for i in range(start_step + 1, cfg.ntime + 1):
        if cfg.heartbeat_every and i % cfg.heartbeat_every == 0:
            master_print(" time_it:", i)  # fortran/serial/heat.f90:62
        if cfg.bc == "edges":
            T = step_edges_np(T, r)
        elif cfg.bc == "periodic":
            T = step_periodic_np(T, r)
        else:
            T = step_ghost_np(T, r, dt(cfg.bc_value))
        if plan is not None:
            plan.maybe_crash(i)
            T = plan.maybe_nan(i, T)
        if cfg.check_numerics:
            debug.check_finite(T, i)  # per step: name the blow-up step and
                                      # never checkpoint a NaN field
        if cfg.checkpoint_every and i % cfg.checkpoint_every == 0:
            checkpoint.save(cfg, T, i)
    solve_s = time.perf_counter() - t0

    gsum = float(T.sum(dtype=np.float64)) if cfg.report_sum else None
    timing = Timing(total_s=time.perf_counter() - t_all0, solve_s=solve_s,
                    steps=cfg.ntime - start_step, points=cfg.points)
    return SolveResult(cfg=cfg, T=T, timing=timing, gsum=gsum,
                       gsum_dtype="float64" if gsum is not None else None,
                       start_step=start_step)
