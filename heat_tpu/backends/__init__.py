"""Backend registry.

The reference implements each programming model as a standalone program
(duplication *is* its architecture, SURVEY.md §2); here the variants are
pluggable backends behind one registry, keyed by names mirroring the
reference taxonomy:

- ``serial``  : numpy oracle            (== fortran/serial, python/serial)
- ``xla``     : jnp + jit, one device   (== cuda_cuf: compiler-generated kernel)
- ``pallas``  : hand-written TPU kernel (== cuda_kernel, hip heat_kernel.cpp)
- ``sharded`` : shard_map + ppermute halo exchange over a device mesh
                (== mpi+cuda / hip MPI layer)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import numpy as np

from ..config import HeatConfig
from ..runtime.timing import Timing

_REGISTRY: Dict[str, Callable] = {}


@dataclasses.dataclass
class SolveResult:
    cfg: HeatConfig
    T: Optional[np.ndarray]  # final field on host; None when the global
                             # array spans other processes (multi-host) or
                             # the caller skipped the fetch — use T_dev +
                             # per-shard IO then
    timing: Timing
    gsum: Optional[float] = None   # global temperature sum if report_sum
    gsum_dtype: Optional[str] = None  # accumulation dtype of gsum ("float64"
                                   # host path / "float32" on-device without
                                   # x64) — label so consumers never compare
                                   # sums across accumulation precisions
    start_step: int = 0            # nonzero when resumed from checkpoint
    mesh_shape: Optional[tuple] = None  # decomposition used (sharded backend)
    T_dev: Any = None              # final field on device (jax.Array)
    mesh: Any = None               # jax.sharding.Mesh (sharded backend)
    guard: Any = None              # sharded.GuardReport when the compile
                                   # guard probed (probe cost, timeout
                                   # verdict, orphan disposition) — bench
                                   # rows must surface a degraded program


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def get_backend(name: str) -> Callable:
    # import lazily so e.g. the numpy oracle works without a functioning JAX
    from . import serial_np, xla, pallas, sharded  # noqa: F401

    if name not in _REGISTRY:
        raise KeyError(f"unknown backend {name!r}; available: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def solve(cfg: HeatConfig, T0: Optional[np.ndarray] = None, **kw) -> SolveResult:
    """Run the configured backend end to end."""
    return get_backend(cfg.backend)(cfg, T0=T0, **kw)
