"""Single-device Pallas backend.

The hand-written-kernel variant: analog of the reference's explicit CUDA
Fortran kernel (fortran/cuda_kernel/heat.F90) and the HIP C++ kernels
(fortran/hip/heat_kernel.cpp). Shares the chunked driver with the XLA
backend; only the per-step kernel differs. Arbitrary grid shapes run
through the kernel via internal alignment padding; only f64 (unsupported on
the TPU vector unit) falls back to the XLA step.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import numpy as np

from ..config import HeatConfig
from ..ops.pallas_stencil import (
    ftcs_multistep_edges_pallas,
    ftcs_multistep_ghost_pallas,
    ftcs_multistep_periodic_pallas,
    ftcs_step_edges_pallas,
    ftcs_step_ghost_pallas,
    ftcs_step_periodic_pallas,
)
from ..ops.stencil import run_steps
from . import SolveResult, register
from .common import drive, resolve_initial_field

# default temporal-blocking depth: amortizes the kernel's per-pass HBM
# traffic over 16 steps (measured throughput on v5e is flat past 16); the
# kernels chunk internally if asked for more than a pass affords
_AUTO_FUSE = 16


def fuse_depth(cfg: HeatConfig) -> int:
    if cfg.fuse_steps:
        return cfg.fuse_steps
    if cfg.dtype != "float64":
        return _AUTO_FUSE  # 3D chunks itself down to what VMEM affords
    return 1


def make_advance(cfg: HeatConfig):
    r = cfg.r
    bc_value = cfg.bc_value
    kf = fuse_depth(cfg)

    if cfg.bc == "edges":
        step = lambda t: ftcs_step_edges_pallas(t, r)
        multi = lambda t, k: ftcs_multistep_edges_pallas(t, r, k)
    elif cfg.bc == "periodic":
        step = lambda t: ftcs_step_periodic_pallas(t, r)
        multi = lambda t, k: ftcs_multistep_periodic_pallas(t, r, k)
    else:
        step = lambda t: ftcs_step_ghost_pallas(t, r, bc_value)
        multi = lambda t, k: ftcs_multistep_ghost_pallas(t, r, bc_value, k)

    @functools.partial(jax.jit, static_argnums=1, donate_argnums=0)
    def advance(T, k: int):
        n_fused, rem = divmod(k, kf)
        if kf > 1 and n_fused:
            T = jax.lax.fori_loop(0, n_fused, lambda i, t: multi(t, kf), T)
        return run_steps(T, rem if kf > 1 else k, step)

    return advance


@register("pallas")
def solve(cfg: HeatConfig, T0: Optional[np.ndarray] = None,
          fetch: bool = True, warm_exec: bool = False,
          two_point_repeats: int = 0, **_) -> SolveResult:
    T, start_step = resolve_initial_field(cfg, T0)
    return drive(cfg, T, make_advance(cfg), start_step=start_step, fetch=fetch,
                 warm_exec=warm_exec, two_point_repeats=two_point_repeats)
