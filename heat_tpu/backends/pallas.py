"""Single-device Pallas backend.

The hand-written-kernel variant: analog of the reference's explicit CUDA
Fortran kernel (fortran/cuda_kernel/heat.F90) and the HIP C++ kernels
(fortran/hip/heat_kernel.cpp). Shares the chunked driver with the XLA
backend; only the per-step kernel differs. Falls back to the XLA step for
shapes the kernel doesn't tile (non-128-multiple columns, f64).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import HeatConfig
from ..ops.pallas_stencil import ftcs_step_edges_pallas, ftcs_step_ghost_pallas
from ..ops.stencil import run_steps
from ..utils import jnp_dtype
from . import SolveResult, register
from .common import drive, load_or_init


def make_advance(cfg: HeatConfig):
    r = cfg.r
    bc_value = cfg.bc_value

    if cfg.bc == "edges":
        step = lambda t: ftcs_step_edges_pallas(t, r)
    else:
        step = lambda t: ftcs_step_ghost_pallas(t, r, bc_value)

    @functools.partial(jax.jit, static_argnums=1, donate_argnums=0)
    def advance(T, k: int):
        return run_steps(T, k, step)

    return advance


@register("pallas")
def solve(cfg: HeatConfig, T0: Optional[np.ndarray] = None, **_) -> SolveResult:
    dt = jnp_dtype(cfg.dtype)
    T0_host, start_step = load_or_init(cfg, T0)
    T = jax.device_put(jnp.asarray(T0_host).astype(dt))
    return drive(cfg, T, make_advance(cfg), start_step=start_step)
