"""Single-device XLA backend.

The compiler-generated-kernel variant: the analog of the reference's CUF
directive solver (``fortran/cuda_cuf/heat.F90:31-38``), where the programmer
writes the loop nest and the compiler builds the device kernel. Here the
"directive" is ``jax.jit``: the shifted-slice stencil in ``ops.stencil``
fuses into one bandwidth-bound XLA kernel; ``lax.fori_loop`` + donation give
a zero-copy double buffer.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import numpy as np

from ..config import HeatConfig
from ..ops.stencil import (ftcs_step_edges, ftcs_step_ghost,
                           ftcs_step_periodic, run_steps)
from . import SolveResult, register
from .common import drive, resolve_initial_field


def make_advance(cfg: HeatConfig):
    """Build the jitted k-step advance function for single-device solves."""
    r = cfg.r
    bc_value = cfg.bc_value

    if cfg.bc == "edges":
        step = lambda t: ftcs_step_edges(t, r)
    elif cfg.bc == "periodic":
        step = lambda t: ftcs_step_periodic(t, r)
    else:
        step = lambda t: ftcs_step_ghost(t, r, bc_value)

    @functools.partial(jax.jit, static_argnums=1, donate_argnums=0)
    def advance(T, k: int):
        return run_steps(T, k, step)

    return advance


@register("xla")
def solve(cfg: HeatConfig, T0: Optional[np.ndarray] = None,
          fetch: bool = True, warm_exec: bool = False,
          two_point_repeats: int = 0, **_) -> SolveResult:
    T, start_step = resolve_initial_field(cfg, T0)
    return drive(cfg, T, make_advance(cfg), start_step=start_step, fetch=fetch,
                 warm_exec=warm_exec, two_point_repeats=two_point_repeats)
