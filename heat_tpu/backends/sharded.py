"""Distributed backend: shard_map + ppermute halo exchange over a device mesh.

The TPU-native rebuild of the reference's distributed flagship
(``fortran/mpi+cuda/heat.F90``) and its HIP twin: the global field is one
jax.Array sharded over a named mesh; each timestep every shard refreshes a
one-cell ghost ring from its neighbors (``parallel.halo``) and applies the
FTCS update to all owned cells. SPMD is JAX's native model — the "same
binary on every rank" structure of the reference comes for free.

Step ordering: the reference updates then swaps (update-then-swap,
fortran/mpi+cuda/heat.F90:206-219), relying on ICs pre-filling the ghosts for
the first step; we default to the causally-clean swap-then-update. For every
shipped IC the two orders are *numerically identical* (the IC ghost values
equal what the first exchange delivers); ``parity_order=True`` requests the
reference's literal ordering, which we honor by noting the equivalence —
both orders share this implementation.

BC semantics:
- ``ghost`` (MPI parity): all owned cells update; global-edge ghosts pinned
  at ``bc_value`` (fortran/mpi+cuda/heat.F90:243-251).
- ``edges`` (serial parity): ditto, then cells on the global boundary ring
  are frozen back — the decomposed run matches the serial oracle bit-for-bit
  in f64.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # JAX >= 0.4.35 exposes shard_map at top level
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from ..config import HeatConfig
from ..ops.stencil import accum_dtype_for, laplacian_interior, run_steps
from ..parallel.halo import global_cell_index, halo_exchange, halo_pad
from ..parallel.mesh import build_mesh, validate_divisible
from ..runtime.logging import master_print
from ..utils import jnp_dtype
from . import SolveResult, register
from .common import drive, load_or_init


def make_local_step(cfg: HeatConfig, axis_names, axis_sizes):
    """Per-shard, per-step function (runs inside shard_map)."""
    r = cfg.r
    bc_value = cfg.bc_value
    staged = cfg.comm == "staged"
    n = cfg.n

    def local_step(local: jax.Array) -> jax.Array:
        acc_dt = accum_dtype_for(local.dtype)
        padded = halo_pad(local, bc_value)
        padded = halo_exchange(padded, axis_names, axis_sizes, bc_value,
                               staged=staged)
        new = (local.astype(acc_dt)
               + jnp.asarray(r, acc_dt) * laplacian_interior(padded)
               ).astype(local.dtype)
        if cfg.bc == "edges":
            gidx = global_cell_index(local.shape, axis_names)
            boundary = functools.reduce(
                jnp.logical_or,
                [(g == 0) | (g == n - 1) for g in gidx],
            )
            new = jnp.where(boundary, local, new)
        return new

    return local_step


def make_advance(cfg: HeatConfig, mesh):
    axis_names = mesh.axis_names
    axis_sizes = mesh.devices.shape
    local_step = make_local_step(cfg, axis_names, axis_sizes)
    spec = P(*axis_names)

    @functools.partial(jax.jit, static_argnums=1, donate_argnums=0)
    def advance(Tg, k: int):
        def body(local):
            return run_steps(local, k, local_step)

        return shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec,
                         check_vma=False)(Tg)

    return advance


@register("sharded")
def solve(cfg: HeatConfig, T0: Optional[np.ndarray] = None, mesh=None, **_) -> SolveResult:
    dt = jnp_dtype(cfg.dtype)
    mesh = mesh or build_mesh(cfg.ndim, cfg.mesh_shape)
    validate_divisible(cfg.n, mesh)
    master_print(f"Automatic mesh decomposition: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

    T0_host, start_step = load_or_init(cfg, T0)
    sharding = NamedSharding(mesh, P(*mesh.axis_names))
    T = jax.device_put(jnp.asarray(T0_host).astype(dt), sharding)
    res = drive(cfg, T, make_advance(cfg, mesh), start_step=start_step)
    res.mesh_shape = tuple(mesh.devices.shape)
    return res
