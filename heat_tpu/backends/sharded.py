"""Distributed backend: shard_map + ppermute halo exchange over a device mesh.

The TPU-native rebuild of the reference's distributed flagship
(``fortran/mpi+cuda/heat.F90``) and its HIP twin: the global field is one
jax.Array sharded over a named mesh; each timestep every shard refreshes a
one-cell ghost ring from its neighbors (``parallel.halo``) and applies the
FTCS update to all owned cells. SPMD is JAX's native model — the "same
binary on every rank" structure of the reference comes for free.

Step ordering: the reference updates then swaps (update-then-swap,
fortran/mpi+cuda/heat.F90:206-219), relying on ICs pre-filling the ghosts for
the first step; we default to the causally-clean swap-then-update.
``parity_order=True`` runs the literal reference ordering instead
(``make_parity_machinery``): the padded field is the carried state, every
step updates owned cells against ghosts as-they-are, then swaps. IC starts
bit-match the default order (the IC fills ghosts with exactly what the
first exchange delivers); explicit-T0 starts expose the reference's
stale-ghost first step, where the orders genuinely diverge — see
tests/test_parity_order.py for the literal transcription oracle.

BC semantics:
- ``ghost`` (MPI parity): all owned cells update; global-edge ghosts pinned
  at ``bc_value`` (fortran/mpi+cuda/heat.F90:243-251).
- ``edges`` (serial parity): ditto, then cells on the global boundary ring
  are frozen back — the decomposed run matches the serial oracle bit-for-bit
  in f64.
- ``periodic``: the ppermute ring closes (last shard exchanges with first)
  and nothing is pinned — the ``pbc=.true.`` cartesian topology the
  reference's communicator is built for but never enables
  (fortran/mpi+cuda/heat.F90:76,97).
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import os
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # JAX >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

# The replication-check kwarg was renamed across JAX releases (check_rep ->
# check_vma). Resolve the spelling THIS jax accepts once, so the call sites
# below stay on the current name and older installs (0.4.x: the CPU test
# matrix) don't lose the whole sharded backend to a TypeError.
import inspect as _inspect

try:
    _shmap_params = _inspect.signature(_shard_map).parameters
except (TypeError, ValueError):  # pragma: no cover — opaque callable
    _shmap_params = {"check_vma": None}
if "check_vma" in _shmap_params:
    shard_map = _shard_map
else:
    _legacy_kw = "check_rep" if "check_rep" in _shmap_params else None

    def shard_map(*args, check_vma=None, **kw):
        if check_vma is not None and _legacy_kw is not None:
            kw[_legacy_kw] = check_vma
        return _shard_map(*args, **kw)

from ..config import HeatConfig
from ..ops.pallas_stencil import (_KMAX_2D, _NO_FREEZE,
                                  ftcs_multistep_bounded_pallas,
                                  pallas_available)
from ..ops.stencil import accum_dtype_for, laplacian_interior
from ..parallel.halo import (halo_exchange, halo_exchange_indep, halo_pad,
                             halo_recvs)
from ..parallel.mesh import build_mesh, validate_divisible
from ..runtime.logging import master_print
from ..utils import jnp_dtype
from . import SolveResult, register
from .common import drive, host_fetch, resolve_initial_field


def make_local_multistep(cfg: HeatConfig, axis_names, axis_sizes):
    """Build ``local_multi(local, w)``: one halo exchange of width w, then w
    fused FTCS steps — the communication-avoiding scheme (runs inside
    shard_map). w=1 is exactly the reference's every-step exchange
    (fortran/mpi+cuda/heat.F90:206-219); w>1 trades a k-deep halo (bigger
    message, same count/k) for k-fewer collectives, with owned-cell values
    bit-identical because ghost layer L is mathematically valid for the
    first w-L mini-steps — precisely when it is read.
    """
    r = cfg.r
    bc_value = cfg.bc_value
    staged = cfg.comm == "staged"
    periodic = cfg.bc == "periodic"
    n = cfg.n

    kernel_ok = pallas_available((cfg.n,) * cfg.ndim, jnp_dtype(cfg.dtype))
    if cfg.local_kernel == "pallas" and not kernel_ok:
        raise ValueError(
            f"local_kernel='pallas' does not support dtype={cfg.dtype!r} "
            f"(no f64 on the TPU VPU); use local_kernel='xla' or 'auto'")
    use_pallas = cfg.local_kernel == "pallas" or (
        cfg.local_kernel == "auto"
        and jax.default_backend() == "tpu"
        and kernel_ok
    )

    overlap = cfg.exchange == "overlap"
    if overlap and not use_pallas:
        raise ValueError(
            "exchange='overlap' requires the Pallas local kernel (the "
            "interior/rim split is built on the bounded multistep kernel); "
            "use local_kernel='pallas', or exchange='indep'")
    # overlap uses the indep ghost writes for the exchange itself (fewest
    # full-shard copies, bit-identical — tests/test_sharded.py)
    exchange_fn = (halo_exchange_indep if cfg.exchange in ("indep", "overlap")
                   else halo_exchange)

    def _shard_bounds(padded_shape, wpad: int) -> list:
        """Per-axis [lo, hi] freeze bounds in PADDED shard coordinates:
        only global-domain edges freeze (Dirichlet ghosts, plus the
        boundary ring under "edges" semantics); the wpad-cell discard
        margin owns all array-edge garbage. Traced values (axis_index)."""
        edges = 1 if cfg.bc == "edges" else 0
        bounds = []
        for d, name in enumerate(axis_names):
            if periodic:  # torus: nothing frozen anywhere
                bounds.extend([jnp.int32(-_NO_FREEZE),
                               jnp.int32(_NO_FREEZE)])
                continue
            coord = jax.lax.axis_index(name)
            M = padded_shape[d]
            bounds.append(jnp.where(coord == 0, wpad - 1 + edges, -1))
            bounds.append(jnp.where(coord == axis_sizes[d] - 1,
                                    M - wpad - edges, M))
        return bounds

    def padded_multi(padded: jax.Array, wpad: int, ksteps: int) -> jax.Array:
        """Exchange the width-``wpad`` ghost ring, then run ``ksteps`` <=
        wpad fused steps; input AND output are the full padded shard (the
        output's ghost margins are garbage — the next exchange rewrites
        every margin cell before anything reads them). This is the
        pad-free core: the padded-carry solve path calls it directly so
        the per-exchange pad+crop copy of the whole block disappears."""
        padded0 = exchange_fn(
            padded, axis_names, axis_sizes, bc_value,
            staged=staged, width=wpad, periodic=periodic,
        )
        if use_pallas:
            bounds = _shard_bounds(padded.shape, wpad)
            return ftcs_multistep_bounded_pallas(
                padded0, r, ksteps, jnp.stack(bounds).astype(jnp.int32))

        acc_dt = accum_dtype_for(padded.dtype)
        rr = jnp.asarray(r, acc_dt)
        if periodic:
            pinned = None  # torus: no Dirichlet ghosts, no frozen ring
        else:
            # global index of every padded cell; exterior (< 0 or >= n)
            # cells are true Dirichlet ghosts
            gidx = []
            for d, name in enumerate(axis_names):
                coord = jax.lax.axis_index(name)
                base = coord * (padded.shape[d] - 2 * wpad) - wpad
                gidx.append(base + jax.lax.broadcasted_iota(
                    jnp.int32, padded0.shape, d))
            exterior = functools.reduce(
                jnp.logical_or, [(g < 0) | (g > n - 1) for g in gidx])
            if cfg.bc == "edges":
                boundary = functools.reduce(
                    jnp.logical_or, [(g == 0) | (g == n - 1) for g in gidx])
                pinned = exterior | boundary
            else:
                pinned = exterior

        def mini_step(p):
            # clamp-pad so the outermost ring has *some* neighbor value; its
            # update is garbage but sits beyond every layer any valid cell
            # reads afterwards (periodic included: ghost layer L is valid
            # for the first wpad-L mini-steps, exactly when it is read)
            clamped = jnp.pad(p, 1, mode="edge")
            new = (p.astype(acc_dt)
                   + rr * laplacian_interior(clamped)).astype(p.dtype)
            if pinned is None:
                return new
            # exterior ghosts stay Dirichlet; edges-BC boundary ring stays
            # at its (never-changing) initial value
            return jnp.where(pinned, padded0, new)

        p = padded0
        for _ in range(ksteps):  # static unroll
            p = mini_step(p)
        return p

    def _set(out, src, dst_sl, src_sl):
        # all slicing is static; skip degenerate spans (tiny shards).
        # Shared by both overlap formulations below.
        if any(s.stop <= s.start for s in dst_sl):
            return out
        return out.at[tuple(dst_sl)].set(src[tuple(src_sl)])

    def padded_multi_overlap(padded: jax.Array, wpad: int,
                             ksteps: int) -> jax.Array:
        """``padded_multi`` restructured so the halo exchange can fly
        while the interior computes (SURVEY.md §7's "hard part"; VERDICT
        r3 #5). Same contract, bit-identical owned values (pinned by
        tests/test_overlap.py and dryrun sub-check #12).

        The sequential form is exchange -> kernel: every cell waits on the
        collectives. Here the fused block splits three ways:

        1. **Interior** (owned cells >= wpad from the shard edge): their
           ksteps<=wpad dependency cone reads only initial values of cells
           at distance >= 0 — by the margin argument a cell at distance s
           contributes only its step-(k-s) value, so distance-wpad cells
           contribute initial values only and NO fresh ghost (and no
           freeze mask) is ever consulted. Computed from the PRE-exchange
           field: zero data dependency on the collectives, so XLA's
           latency-hiding scheduler is free to hoist the ppermute starts
           before it and sink the dones after it.
        2. **Exchange**: the indep RECEIVES (halo_recvs) kept as separate
           per-face slabs — never written into one array on this path. A
           rim kernel slicing the fully-written array would depend on
           EVERY collective; round 4 shipped exactly that and the
           flagship schedule census showed the cost: 1 kernel in flight
           of 7, 3 of 4 windows empty
           (benchmarks/topology_schedule_flagship_f16.json).
        3. **Boundary regions** (round 5, the narrow-dependency rework):
           the owned rim splits into the 3^nd - 1 regions of cells within
           wpad of each face subset. A FACE region's input is assembled
           from pre-exchange data + that one axis's ghost slab only, so
           its only wire dependency is its own axis's ppermutes — the
           scheduler can run it inside the other axes' flight windows
           (the recvs chain is sequential by axis: axis d's sends stitch
           e<d's fresh corners). Edge/corner regions (3*wpad per nonzero
           axis) depend on exactly their axes' slabs and are tiny.
           Band-edge garbage travels one cell per mini-step and never
           reaches a kept cell (distance >= wpad >= ksteps), the same
           invariant as the exchange itself. Tiny shards (local < 2*wpad,
           where a 3*wpad input would cross into the far ghost margin)
           take the round-4 wide formulation (_overlap_wide) instead.

        Extra compute vs the fused form: the regions re-cover ~8*wpad/L
        of the block (1.6% at 16384^2, wpad=32) plus the extra kernel
        launches per block; the win is the exchange latency hidden behind
        the interior and prior-axis face passes. Kept-region writes are
        disjoint and complete by construction (each owned cell's region
        is determined by its per-axis rim membership)."""
        w = wpad
        nd = padded.ndim
        Lp = padded.shape

        # 1) interior, from the PRE-exchange field
        owned = padded[tuple(slice(w, -w) for _ in range(nd))]
        nofreeze = jnp.asarray([-_NO_FREEZE, _NO_FREEZE] * nd, jnp.int32)
        interior = ftcs_multistep_bounded_pallas(owned, r, ksteps, nofreeze)
        bounds = _shard_bounds(Lp, w)

        if any(Lp[d] - 2 * w < 2 * w for d in range(nd)):
            # tiny shard (local < 2w): the narrow-dep region inputs below
            # would reach into the FAR ghost margin (garbage inside the
            # kept cone) — use the wide formulation: exchange fully, rim
            # bands slice the written array
            return _overlap_wide(padded, interior, bounds, w, ksteps)

        # 2) per-face receive slabs — NOT written into one array: a rim
        # kernel that slices the fully-written array depends on EVERY
        # collective and can never enter a flight window (round-4 census:
        # 1 kernel in flight of 7, 3 of 4 windows empty —
        # topology_schedule_flagship_f16.json). Each region below is
        # assembled from only the slabs its kept cells read, so a face
        # band's only wire dependency is its OWN axis's ppermutes and the
        # scheduler is free to run it inside other axes' windows.
        recvs = halo_recvs(padded, axis_names, axis_sizes, bc_value,
                           staged=staged, width=w, periodic=periodic)

        def region_input(sigma):
            """Region ``sigma`` in {-1,0,+1}^nd: cells within w of the
            faces sigma marks. Input = pre-exchange data + ONLY those
            faces' fresh ghost slabs, overwritten in increasing axis
            order (same last-write-wins corner ownership as
            apply_recvs)."""
            src = []
            for d, s in enumerate(sigma):
                src.append(slice(w, Lp[d] - w) if s == 0
                           else slice(0, 3 * w) if s < 0
                           else slice(Lp[d] - 3 * w, Lp[d]))
            I = padded[tuple(src)]
            for d, s in enumerate(sigma):
                if s == 0:
                    continue
                slab = recvs[d][0 if s < 0 else 1]
                g_sl = []
                for e, se in enumerate(sigma):
                    if e == d:
                        g_sl.append(slice(None))  # slab is w deep on d
                    elif se == 0:
                        g_sl.append(slice(w, Lp[e] - w))
                    elif se < 0:
                        g_sl.append(slice(0, 3 * w))
                    else:
                        g_sl.append(slice(Lp[e] - 3 * w, Lp[e]))
                dst = [slice(None)] * nd
                dst[d] = slice(0, w) if s < 0 else slice(2 * w, 3 * w)
                I = I.at[tuple(dst)].set(slab[tuple(g_sl)])
            return I

        # output bases on the PRE-exchange array: every owned cell is
        # overwritten below, and the ghost margins are garbage by contract
        # (the next exchange rewrites every margin cell before any read)
        out = padded
        # interior kept: owned cells at distance >= w (padded [2w, Lp-2w))
        out = _set(out, interior,
                   [slice(2 * w, Lp[d] - 2 * w) for d in range(nd)],
                   [slice(w, Lp[d] - 3 * w) for d in range(nd)])
        # 3) all 3^nd - 1 boundary regions: faces (one nonzero — depend on
        # one axis's wire only), then edges/corners (tiny, multi-axis)
        for sigma in itertools.product((-1, 0, 1), repeat=nd):
            if not any(sigma):
                continue
            off = [0 if s < 0 else Lp[d] - 3 * w if s > 0 else w
                   for d, s in enumerate(sigma)]
            bnd = list(bounds)
            for d in range(nd):
                bnd[2 * d] = bnd[2 * d] - off[d]
                bnd[2 * d + 1] = bnd[2 * d + 1] - off[d]
            band = ftcs_multistep_bounded_pallas(
                region_input(sigma), r, ksteps,
                jnp.stack(bnd).astype(jnp.int32))
            sl_keep, sl_dst = [], []
            for d, s in enumerate(sigma):
                if s == 0:  # clear of every face of this axis
                    sl_keep.append(slice(w, Lp[d] - 3 * w))
                    sl_dst.append(slice(2 * w, Lp[d] - 2 * w))
                else:       # the w-deep owned rim of this face
                    sl_keep.append(slice(w, 2 * w))
                    sl_dst.append(slice(w, 2 * w) if s < 0
                                  else slice(Lp[d] - 2 * w, Lp[d] - w))
            out = _set(out, band, sl_dst, sl_keep)
        return out

    def _overlap_wide(padded, interior, bounds, w, ksteps):
        """Round-4 overlap shape for tiny shards: full exchange, rim
        bands slice the written array (every band waits on all wires —
        immaterial at sizes where bands ARE most of the shard)."""
        nd = padded.ndim
        Lp = padded.shape

        padded0 = exchange_fn(
            padded, axis_names, axis_sizes, bc_value,
            staged=staged, width=w, periodic=periodic,
        )
        out = padded0
        out = _set(out, interior,
                   [slice(2 * w, Lp[d] - 2 * w) for d in range(nd)],
                   [slice(w, Lp[d] - 3 * w) for d in range(nd)])
        for d in range(nd):
            for lo in (True, False):
                off = 0 if lo else Lp[d] - 3 * w
                sl_in = [slice(None)] * nd
                sl_in[d] = slice(off, off + 3 * w)
                bnd = list(bounds)
                bnd[2 * d] = bnd[2 * d] - off
                bnd[2 * d + 1] = bnd[2 * d + 1] - off
                band = ftcs_multistep_bounded_pallas(
                    padded0[tuple(sl_in)], r, ksteps,
                    jnp.stack(bnd).astype(jnp.int32))
                sl_keep = [slice(None)] * nd
                sl_dst = [slice(None)] * nd
                for e in range(nd):
                    if e == d:  # this face's w-deep owned rim
                        sl_keep[e] = slice(w, 2 * w)
                        sl_dst[e] = (slice(w, 2 * w) if lo
                                     else slice(Lp[d] - 2 * w, Lp[d] - w))
                    elif e < d:  # earlier axes' bands own the corners
                        sl_keep[e] = slice(2 * w, Lp[e] - 2 * w)
                        sl_dst[e] = sl_keep[e]
                    else:  # later axes: full owned span (incl. corners)
                        sl_keep[e] = slice(w, Lp[e] - w)
                        sl_dst[e] = sl_keep[e]
                out = _set(out, band, sl_dst, sl_keep)
        return out

    if overlap:
        padded_multi = padded_multi_overlap

    def local_multi(local: jax.Array, w: int) -> jax.Array:
        out = padded_multi(halo_pad(local, bc_value, w), w, w)
        ctr = tuple(slice(w, -w) for _ in range(out.ndim))
        return out[ctr]

    return local_multi, padded_multi


def make_parity_machinery(cfg: HeatConfig, mesh):
    """Literal update-then-swap stepping (fortran/mpi+cuda/heat.F90:206-219).

    Unlike the default communication-avoiding order (exchange, then update),
    the reference updates every owned cell against the ghosts *as they are*,
    then swaps. That forces the ghost ring to be carried state: here the
    sharded global array is the PADDED field (each shard = owned + width-1
    ghosts), exactly the reference's ``(1-ng:nx+ng, 1-ng:ny+ng)`` per-rank
    allocation (:107).

    Ghost seeding decides whether the orders are distinguishable:
    - IC starts seed ghosts by one exchange — identical to the reference's
      whole-padded-array IC fill (``T = 2.0`` at :243 evaluates the IC at
      ghost coordinates too), so shipped-IC runs bit-match the default
      order (the equivalence round 1 claimed, now executable).
    - explicit-T0 starts seed ghosts with ``bc_value`` only (nothing fills
      them, as in a raw restart): the first update reads stale ghosts and
      the two orders genuinely diverge — the reference's latent
      stale-first-step behavior, made observable.

    Returns (seed, advance, crop): seed builds the padded global from the
    owned global, advance runs k literal steps, crop recovers the owned
    global.
    """
    axis_names = mesh.axis_names
    axis_sizes = mesh.devices.shape
    r = cfg.r
    bc_value = cfg.bc_value
    staged = cfg.comm == "staged"
    periodic = cfg.bc == "periodic"
    n = cfg.n
    # bit-identical formulations (tests/test_sharded.py pins it), so the
    # literal update-then-swap ordering is preserved either way; "overlap"
    # has no meaning at w=1 parity stepping — it gets indep's exchange
    exchange_fn = (halo_exchange_indep if cfg.exchange in ("indep", "overlap")
                   else halo_exchange)
    spec = P(*axis_names)
    smap = functools.partial(shard_map, mesh=mesh, in_specs=(spec,),
                             out_specs=spec, check_vma=False)

    def _pinned_mask(padded):
        # cells the update must never change: the ghost ring itself (w=1),
        # plus the global boundary ring under "edges" semantics
        gidx = []
        for d, name in enumerate(axis_names):
            coord = jax.lax.axis_index(name)
            base = coord * (padded.shape[d] - 2) - 1
            gidx.append(base + jax.lax.broadcasted_iota(
                jnp.int32, padded.shape, d))
        ghost = functools.reduce(
            jnp.logical_or, [(g < 0) | (g > n - 1) for g in gidx])
        if cfg.bc == "edges":
            ring = functools.reduce(
                jnp.logical_or, [(g == 0) | (g == n - 1) for g in gidx])
            return ghost | ring
        return ghost

    def local_parity_step(padded):
        acc_dt = accum_dtype_for(padded.dtype)
        rr = jnp.asarray(r, acc_dt)
        lap = laplacian_interior(padded)  # owned region, reading ghosts
        new = padded.astype(acc_dt)
        ctr = tuple(slice(1, -1) for _ in range(padded.ndim))
        new = new.at[ctr].add(rr * lap)
        new = jnp.where(_pinned_mask(padded), padded,
                        new.astype(padded.dtype))
        # ghost update AFTER the stencil — the literal :218 ``call swap()``
        return exchange_fn(new, axis_names, axis_sizes, bc_value,
                           staged=staged, width=1, periodic=periodic)

    def seed(T_owned: jax.Array, from_ic: bool) -> jax.Array:
        def body(local):
            padded = halo_pad(local, bc_value, 1)
            if from_ic:
                padded = exchange_fn(padded, axis_names, axis_sizes,
                                     bc_value, staged=staged, width=1,
                                     periodic=periodic)
            return padded

        return jax.jit(smap(body))(T_owned)

    @functools.partial(jax.jit, static_argnums=1, donate_argnums=0)
    def advance(Tp, k: int):
        def body(padded):
            return jax.lax.fori_loop(
                0, k, lambda i, t: local_parity_step(t), padded)

        return smap(body)(Tp)

    @jax.jit
    def crop(Tp):
        return smap(
            lambda p: p[tuple(slice(1, -1) for _ in range(p.ndim))])(Tp)

    return seed, advance, crop


def _solve_parity(cfg: HeatConfig, T0, mesh, fetch: bool, warm_exec: bool):
    """Parity-ordered solve path (cfg.parity_order)."""
    if cfg.checkpoint_every:
        raise ValueError(
            "parity_order is a bit-parity experiment mode and does not "
            "support checkpointing (the carried state is the padded field)")
    master_print("step ordering: update-then-swap "
                 "(reference parity, mpi+cuda/heat.F90:206-219)")
    sharding = NamedSharding(mesh, P(*mesh.axis_names))
    T_owned, start_step = resolve_initial_field(cfg, T0, sharding=sharding)
    seed, advance, crop = make_parity_machinery(cfg, mesh)
    Tp = seed(T_owned, from_ic=T0 is None)
    res = drive(cfg.with_(report_sum=False), Tp, advance,
                start_step=start_step, fetch=False, warm_exec=warm_exec)
    return _finalize_carried(cfg, res, crop, fetch)


def _finalize_carried(cfg: HeatConfig, res, crop, fetch: bool):
    """Crop a padded-state result back to the owned field and do the
    post-solve accounting (fetch, gsum) the padded state deferred."""
    res.cfg = cfg
    res.T_dev = crop(res.T_dev)
    res.T = host_fetch(res.T_dev) if fetch else None
    if cfg.report_sum:
        if res.T is not None:
            res.gsum = float(np.sum(np.asarray(res.T, np.float64)))
            res.gsum_dtype = "float64"
        else:
            acc = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
            res.gsum = float(np.asarray(jnp.sum(res.T_dev, dtype=acc)))
            res.gsum_dtype = np.dtype(acc).name
    return res


# auto depths above this get the compile guard. Round-4 measured cold
# Mosaic compile times for the auto-picked kernels (chipless
# AOT-topology bisect, benchmarks/compile_bisect_topology*.json):
# flagship-scale fused kernels cost MINUTES cold (16384-local: k=8
# 393 s, k=16 980-2038 s on the TOPOLOGY path vs 471 s live, k=32
# 665 s), and the thin-band deep-unroll family is a genuine cliff
# (8192-local k=32 wedged >36 min before being killed). Round 5 capped
# the auto 2D depth at the kernel's per-pass chunk (16 at flagship
# width — the measured rate optimum), which removes the wedge family
# from the auto path's reach entirely: every auto program now cold-
# compiles in bounded minutes on the live path, so depths <= 16 stay
# unguarded (the probe's topology-path compile of the k=16 flagship
# costs >2000 s — 4x the live compile it would bound; see
# _guard_fuse_compile).
_SAFE_FUSE = 16

# Default probe wall budget. Sized ABOVE every measured cold compile of a
# program the auto planner can pick — the guard exists to catch the
# genuinely wedged family (thin-band deep unroll: >36 min before being
# killed), not to time out legitimate flagship compiles. Measured ceiling:
# the 16384^2 overlap flagship cold-compiles in 1833 s
# (benchmarks/overlap_compile_check.json) — which EXCEEDED the previous
# 1800 s default, so a cold-cache `--exchange overlap` run used to default
# into the fallback (VERDICT r4 weak #1). 2400 s clears it with margin.
_DEFAULT_BUDGET_S = "2400"


@dataclasses.dataclass
class GuardReport:
    """Compile-guard telemetry, attached to ``SolveResult.guard`` whenever
    the guard probed (VERDICT r4 #8: a timed-out probe's cost — and what
    became of the abandoned compile — must be visible in the result a
    bench row consumes, never silently folded away)."""
    probed: bool = False
    probe_mode: Optional[str] = None   # "subprocess" | "thread" |
    #                                    "subprocess->thread" (child failed,
    #                                    thread took over)
    timed_out: bool = False
    budget_s: float = 0.0
    probe_s: float = 0.0               # wall cost, folded into compile_s
    orphan: Optional[str] = None       # timeout only: "killed" (subprocess
    #                                    probe — no compile outlives the
    #                                    solve) | "left_running" (thread
    #                                    probe — background compile persists
    #                                    until it finishes or process exit)
    deserialize_failed: bool = False   # child compiled in budget but the
    #                                    executables didn't transfer; solve
    #                                    proceeds un-degraded and recompiles
    degraded: Optional[dict] = None    # cfg fields the fallback rewrote


def _bounded_compile(fn, budget_s: float):
    """Run ``fn`` (an XLA/Mosaic compile) in a daemon thread with a wall
    budget. Returns (result, None) on success, (None, "timeout") when the
    budget expires — the thread is left running (a C++ compile cannot be
    interrupted from Python; it dies with the process or finishes into
    the persistent compile cache). fn's exceptions propagate.

    The THREAD probe is the fallback mode: the default subprocess probe
    (``_subprocess_probe``) is killable, so a timed-out compile can't
    squat a core under the fallback solve's bench row."""
    box: dict = {}

    def run():
        try:
            box["r"] = fn()
        except BaseException as e:  # noqa: BLE001 — reraised below
            box["e"] = e

    t = threading.Thread(target=run, daemon=True, name="heat-compile-guard")
    t.start()
    t.join(budget_s)
    if t.is_alive():
        return None, "timeout"
    if "e" in box:
        raise box["e"]
    return box.get("r"), None


def _compile_probe(cfg: HeatConfig, mesh, kf: int, remaining: int,
                   padded: bool) -> dict:
    """AOT-compile every program drive() will run — each chunk size from
    the SAME derivation drive uses (common.chunk_sizes: a remainder chunk
    still unrolls the deep-fused kernel and is a distinct XLA program) —
    on the path's actual global state shape. No device buffers, no data
    transfer. Returns {chunk_size: compiled executable}; the caller hands
    it to drive(precompiled=...) so the probe's work is never repeated."""
    from .common import chunk_sizes

    # NOTE on the persistent compile cache: when the user (or the bench
    # harness) sets JAX_COMPILATION_CACHE_DIR, jax honors it natively and
    # an abandoned (timed-out) probe's eventual completion pays forward to
    # a rerun. The guard deliberately does NOT flip the cache on itself —
    # mutating process-global jax config from the probe thread would leak
    # into every later compile (and race the main thread).
    if padded:
        _, advance, _ = make_padded_carry_machinery(cfg, mesh)
    else:
        advance = make_advance(cfg, mesh)
    struct = _probe_state_struct(cfg, mesh, kf, padded)
    return {k: advance.lower(struct, k).compile()
            for k in chunk_sizes(cfg, remaining)}


def _probe_state_struct(cfg: HeatConfig, mesh, kf: int, padded: bool):
    """The sharded state ShapeDtypeStruct the probe compiles against —
    ONE derivation shared by the compile and the subprocess probe's
    validation execution (they must describe the same program input)."""
    shape = (tuple(cfg.n + 2 * kf * int(s) for s in mesh.devices.shape)
             if padded else cfg.shape)
    return jax.ShapeDtypeStruct(
        shape, jnp_dtype(cfg.dtype),
        sharding=NamedSharding(mesh, P(*mesh.axis_names)))


def _subprocess_probe(cfg: HeatConfig, mesh, kf: int, remaining: int,
                      padded: bool, budget_s: float):
    """Killable probe: run ``_compile_probe`` in a child process
    (``guard_probe`` module — chipless topology AOT compile for TPU
    parents, same-platform for CPU test parents) and ship the executables
    back via ``jax.experimental.serialize_executable``. Returns
    ``(pre, status)`` with status in {"ok", "timeout", "deserialize-failed",
    "child-error: ...", "spawn-error: ..."}.

    On timeout the whole child process GROUP is SIGKILLed — unlike the
    thread probe, no abandoned Mosaic compile outlives the budget (the
    orphan-capping contract, VERDICT r4 #8). The serialized executables
    are the only RELIABLE hand-forward mechanism here: for
    Mosaic-kernel programs, topology AOT compiles neither write the
    persistent compile cache (bisect children's per-k cache dirs come
    back empty) nor get served by live-written entries (re-verified
    round 5 against a sweep-warmed cache — the pinned-kernel child
    recompiled from scratch); a topology-compiled plain-XLA program was
    observed to land an entry, but the probe exists precisely for the
    Mosaic family. So a successful child that fails to transfer costs
    one bounded recompile in drive, and a killed child leaves nothing
    behind."""
    import json
    import shutil
    import tempfile

    from .. import machine

    # The child must compile the SAME program drive will run, so the
    # parent RESOLVES every environment-dependent choice and pins it in
    # the spec: the child is a forced-CPU process, where "auto" would
    # silently resolve to the seconds-fast XLA kernel and the guard would
    # bound the wrong program (the round-4 retracted-curve bug,
    # benchmarks/compile_bisect.py's lk-pinning note).
    kernel_ok = pallas_available((cfg.n,) * cfg.ndim, jnp_dtype(cfg.dtype))
    use_pallas = cfg.local_kernel == "pallas" or (
        cfg.local_kernel == "auto"
        and jax.default_backend() == "tpu"
        and kernel_ok)  # same resolution as make_local_multistep
    tmpdir = tempfile.mkdtemp(prefix="heat_guard_probe_")
    spec_path = os.path.join(tmpdir, "spec.json")
    out_path = os.path.join(tmpdir, "pre.pkl")
    spec = {"cfg": {**dataclasses.asdict(cfg),
                    "local_kernel": "pallas" if use_pallas else "xla"},
            "mesh_shape": list(mesh.devices.shape),
            "axis_names": list(mesh.axis_names),
            "kf": kf, "remaining": remaining, "padded": padded,
            "platform": jax.default_backend(),
            "chip": machine.current().name,
            "out": out_path}
    try:
        with open(spec_path, "w") as f:
            json.dump(spec, f)
        return _run_probe_child(spec_path, out_path, mesh, cfg, kf, padded,
                                budget_s)
    finally:
        # pre.pkl holds serialized flagship-scale executables (tens to
        # hundreds of MB); a bench sweep must not fill /tmp with them
        shutil.rmtree(tmpdir, ignore_errors=True)


def _run_probe_child(spec_path: str, out_path: str, mesh, cfg, kf: int,
                     padded: bool, budget_s: float):
    import pickle
    import signal
    import subprocess
    import sys

    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "heat_tpu.backends.guard_probe",
             spec_path],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
            start_new_session=True)  # own group: the kill reaps compiler
        #                              helper processes too
    except OSError as e:
        return None, f"spawn-error: {e}"
    try:
        _, err_txt = proc.communicate(timeout=budget_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):  # already gone
            proc.kill()
        proc.wait()
        return None, "timeout"
    if proc.returncode != 0:
        tail = (err_txt or "").strip().splitlines()[-3:]
        return None, "child-error: " + " | ".join(tail)
    try:
        from jax.experimental import serialize_executable

        with open(out_path, "rb") as f:
            payloads = pickle.load(f)
        devs = list(mesh.devices.flat)
        pre = {k: serialize_executable.deserialize_and_load(
                   ser, in_tree, out_tree, execution_devices=devs)
               for k, (ser, in_tree, out_tree) in payloads.items()}
        # Deserialization alone is NOT proof the executable runs — a
        # cross-process AOT transfer can load cleanly and still fail at
        # dispatch (observed on XLA:CPU: "Function ... not found").
        # Validate with a real execution on a throwaway buffer so drive
        # never discovers a broken executable mid-solve with the state
        # donated into it. Single-process only: the advance is a
        # COLLECTIVE program, and a validation exec entered only by the
        # processes whose deserialize succeeded would hang the others
        # (divergence-safety contract) — multi-host accepts the transfer
        # structurally and lets drive's first chunk surface any fault.
        if jax.process_count() == 1:
            from ..runtime.timing import sync

            struct = _probe_state_struct(cfg, mesh, kf, padded)
            for fn in pre.values():
                sync(fn(jnp.zeros(struct.shape, struct.dtype,
                                  device=struct.sharding)))
        return pre, "ok"
    except Exception as e:  # noqa: BLE001 — the child PROVED the compile
        # fits the budget; failing to transfer the executables must not
        # degrade the solve, only cost a (bounded) recompile in drive
        master_print(f"compile guard: probe executables did not transfer "
                     f"({type(e).__name__}: {e}); drive will recompile")
        return None, "deserialize-failed"


def _agree_any_timeout(timed_out: bool) -> bool:
    """Multi-process agreement on the guard verdict: every process must
    run the SAME advance program (different fuse depths mean different
    halo widths and different collective sequences — a mismatched SPMD
    program hangs the job), so if ANY process's probe timed out, all fall
    back together. Mirrors _agree_resume_step's minimum rule."""
    if jax.process_count() <= 1:
        return timed_out
    from jax.experimental import multihost_utils

    flags = np.asarray(multihost_utils.process_allgather(
        jnp.asarray(int(timed_out), jnp.int32)))
    agreed = bool(flags.max())
    if agreed != timed_out:
        master_print("compile guard: a peer process's probe timed out — "
                     "falling back job-wide")
    return agreed


def _guard_platform_ok() -> bool:
    """The guard only pays for itself where Mosaic compiles can cliff
    (TPU); CPU interpret-mode 'compiles' are trivially bounded. A seam so
    tests can force the guard on without patching jax.default_backend
    globally (which would flip the kernels' interpret-mode detection)."""
    return jax.default_backend() == "tpu"


def _guard_fuse_compile(cfg: HeatConfig, mesh, remaining: int,
                        padded: bool = True):
    """Bound the compile time of the AUTO-selected fuse depth.

    The planner's k* (fuse_depth_sharded) is a throughput optimum with no
    compile-cost term, and deep-unroll Mosaic compiles can cliff (the
    col-tiled band cap note in ops/pallas_stencil.py documents minutes-to
    ->12-minutes growth). A user running the default config must never
    stall unboundedly in compile, so: when the depth was auto-picked and
    exceeds the measured-safe depth, every program drive() will compile is
    compiled under one wall budget (``HEAT_COMPILE_BUDGET_S``, default
    ``_DEFAULT_BUDGET_S`` = 2400 s — sized above the slowest measured
    legitimate cold compile, the 1833 s overlap flagship; 0 disables); on
    timeout the solve falls back to the
    seconds-compiling XLA local kernel with a loud warning, job-wide
    (_agree_any_timeout), and the abandoned Mosaic compile finishes into
    the persistent cache (a rerun gets the kernel for free if it does
    complete). Explicit --fuse-steps or --local-kernel pallas is honored
    unguarded — the user asked for that exact program.

    Returns ``(cfg, precompiled, report)``: on success ``precompiled``
    carries the probe's executables for drive(precompiled=...), so the
    guard costs zero extra compiles; ``report`` is a :class:`GuardReport`
    whose ``probe_s`` is the probe's wall time (drive folds it into the
    reported compile/total time — the guard must not make minutes of
    compile invisible to timing consumers) and whose ``orphan`` field
    records what became of an abandoned compile.

    Probe modes (``HEAT_GUARD_PROBE``): ``subprocess`` (default) runs the
    probe in a killable child (``guard_probe`` module) — on timeout the
    child's process group is SIGKILLed, so no orphan compile outlives the
    solve; ``thread`` restores the round-4 in-thread probe (zero-copy
    executable hand-off, but a timed-out compile keeps burning a core
    until it finishes). A child that FAILS (not times out — e.g. another
    process holds the libtpu lockfile) degrades to the thread probe with
    the remaining budget.

    Divergence safety: every gate before the collective agreement derives
    from cfg/mesh/platform — identical across an SPMD job by contract.
    Per-host state that CAN diverge (the budget env var, probe exceptions,
    probe timing) only feeds the agreed verdict, never whether the
    collective is reached — a process skipping a collective its peers
    entered would hang the job."""
    t0 = time.perf_counter()
    kf = fuse_depth_sharded(cfg, mesh.devices.shape)
    # Trigger stays kf > _SAFE_FUSE (round-4 form) DELIBERATELY, after a
    # round-5 detour through guarding kf == 16: the round-5 per-pass
    # chunk cap means the auto path can no longer reach the >36-min
    # wedge family at all — wide shards cap at k=16, whose LIVE cold
    # compile measured a bounded 471 s (sweep_r5.log 09:21) — while the
    # subprocess probe's topology-path compile of that same program
    # measured >2000 s (the k=16 compile anomaly, live-path cache
    # entries do NOT serve the topology child). Guarding k=16 therefore
    # costs ~4x the compile it bounds and risks timing the default
    # flagship into the degraded kernel; bounded-minutes compiles are
    # not the stall the guard exists for. Auto depths > 16 only arise
    # for narrow shards (chunk cap 32, small bands, fast compiles) and
    # keep the guard as belt-and-braces.
    if (cfg.fuse_steps or kf <= _SAFE_FUSE or remaining <= 0
            or cfg.local_kernel != "auto" or cfg.dtype == "float64"
            or not _guard_platform_ok()):
        # nothing to guard: explicit user program (a requested
        # --local-kernel pallas must never be silently downgraded to xla
        # — that IS the "wait the compile out" remedy the fallback
        # warning advertises), capped auto depth, or the XLA/f64 path
        # (seconds-fast compiles) already chosen
        return cfg, None, GuardReport()
    try:
        budget = float(os.environ.get("HEAT_COMPILE_BUDGET_S",
                                      _DEFAULT_BUDGET_S))
    except ValueError:
        budget = float(_DEFAULT_BUDGET_S)
    mode = os.environ.get("HEAT_GUARD_PROBE", "subprocess")
    if mode != "thread":
        mode = "subprocess"
    rep = GuardReport(probe_mode=mode, budget_s=budget)
    pre, timed_out = None, False
    if budget > 0:  # budget<=0 disables the probe, NOT the agreement
        rep.probed = True  # only now: a budget-0 run never probed, and
        # its SolveResult must not carry a report claiming it did
        from ..utils import ensure_cache_env

        # flagship-scale compiles are exactly when the persistent cache
        # pays: the thread probe's (device-target) compiles and drive's
        # own land where a rerun finds them. NOT the subprocess child's —
        # topology AOT compiles bypass the persistent cache (see
        # _subprocess_probe); there the serialized executables carry the
        # work instead.
        ensure_cache_env()
        if mode == "subprocess":
            pre, status = _subprocess_probe(cfg, mesh, kf, remaining,
                                            padded, budget)
            if status == "timeout":
                timed_out, rep.orphan = True, "killed"
            elif status == "deserialize-failed":
                rep.deserialize_failed = True  # NOT a timeout: the child
                # proved the program compiles in budget; solve proceeds
                # un-degraded and pays one (bounded) recompile in drive
            elif status != "ok":
                # environmental child failure (libtpu lockfile held, spawn
                # error...): degrade to the thread probe with what's left
                # of the budget rather than inventing a verdict
                master_print(f"compile guard: subprocess probe failed "
                             f"({status}); retrying in-thread")
                rep.probe_mode = "subprocess->thread"
                budget_left = budget - (time.perf_counter() - t0)
                if budget_left <= 0:
                    timed_out, rep.orphan = True, None
                else:
                    mode = "thread"
                    budget = budget_left
        if mode == "thread" and not timed_out:
            try:
                pre, err = _bounded_compile(
                    lambda: _compile_probe(cfg, mesh, kf, remaining, padded),
                    budget)
                if err is not None:
                    timed_out, rep.orphan = True, "left_running"
            except Exception as e:  # noqa: BLE001 — a probe crash (e.g.
                # RESOURCE_EXHAUSTED on the deep unroll) means the k*
                # program is unusable here: fall back rather than let
                # drive hit the same error, and NEVER skip the agreement
                # below (peers would hang in the collective)
                master_print(f"compile guard: probe failed "
                             f"({type(e).__name__}: {e}); treating as "
                             f"timeout")
                pre, timed_out = None, True
    # rep.timed_out carries the AGREED verdict (the one that drives the
    # degrade), which can differ from the local probe's outcome job-wide
    rep.timed_out = _agree_any_timeout(timed_out)
    if not rep.timed_out:
        rep.probe_s = time.perf_counter() - t0
        return cfg, pre, rep
    # Fallback must be a program whose compile is KNOWN fast. Shallower
    # Pallas depths are not that: at flagship scale even k=8 cold-compiles
    # in ~6-16 min (compile_bisect_topology.json), so a k=16 fallback
    # would bust the very budget that just expired. The XLA local kernel
    # compiles in seconds at every measured size (same fused exchange
    # structure, ~5x lower per-step throughput) — a slower solve that
    # starts now beats a fast one stuck in Mosaic.
    # Pin the probed depth too: the xla kernel is exempt from the
    # round-5 per-pass chunk cap, so leaving fuse_steps=0 would silently
    # recompute a DIFFERENT depth (flagship: 32 vs the probed 16) and
    # the "same fuse depth" the warning promises — and the exchange
    # cadence/ghost widths any telemetry shows — would not match the
    # program that runs (review r5).
    degrade = {"local_kernel": "xla", "fuse_steps": kf}
    note = ""
    if cfg.exchange == "overlap":
        # overlap is BUILT on the Pallas bounded-multistep kernel
        # (make_local_multistep raises for overlap-without-Pallas), so the
        # exchange must degrade with the kernel — the guard's whole point
        # is that a default run never crashes or stalls unboundedly. indep
        # is bit-identical on owned cells (tests/test_sharded.py), only
        # the interior/rim latency-hiding split is lost.
        degrade["exchange"] = "indep"
        note = (" exchange='overlap' needs that kernel, so the exchange "
                "falls back to 'indep' as well (owned values bit-identical; "
                "only the latency-hiding split is lost).")
    if rep.orphan == "killed":
        orphan_note = ("The abandoned Mosaic compile was killed with the "
                       "probe process.")
    elif rep.orphan == "left_running":
        orphan_note = (
            "The abandoned Mosaic compile continues (and lands in the "
            "compile cache when JAX_COMPILATION_CACHE_DIR is set) — a "
            "rerun may pick the kernel up instantly.")
    elif pre is not None or rep.deserialize_failed:
        # a peer's timeout forced the job-wide fallback but THIS process's
        # probe compile completed — the local cache is already warm
        orphan_note = ("This process's own probe compile completed (a "
                       "peer's timeout forced the job-wide fallback); the "
                       "local compile cache is warm.")
    else:  # probe crashed / failed before compiling anything: there is
        # no background compile and no cache entry to wait for
        orphan_note = "No probe compile was started."
    master_print(
        f"WARNING: auto fuse depth {kf} (Pallas kernel) did not compile "
        f"within {rep.budget_s:.0f}s (HEAT_COMPILE_BUDGET_S); falling back "
        f"to local_kernel='xla' at the same fuse depth — compiles in "
        f"seconds, ~5x lower per-step throughput.{note} {orphan_note} "
        f"Pass --local-kernel pallas to wait the compile out.")
    rep.degraded = degrade
    rep.probe_s = time.perf_counter() - t0
    return cfg.with_(**degrade), None, rep


def _solve_padded_carry(cfg: HeatConfig, T0, mesh, fetch: bool,
                        warm_exec: bool, two_point_repeats: int = 0):
    """Default sharded solve: padded-carry state (make_padded_carry_machinery)."""
    cfg, pre, guard = _guard_fuse_compile(cfg, mesh, cfg.ntime, padded=True)
    sharding = NamedSharding(mesh, P(*mesh.axis_names))
    T_owned, start_step = resolve_initial_field(cfg, T0, sharding=sharding)
    # The guard's probe ran BEFORE the field resolved, with
    # remaining=cfg.ntime — correct only while this path never resumes
    # (checkpointed runs take the owned-state path). Fail loudly if a
    # future routing change breaks that convention, rather than silently
    # probing a wrong remainder size and compiling the real one unguarded
    # inside drive (ADVICE r4).
    if start_step != 0:  # explicit raise: an assert vanishes under -O,
        # silently restoring the wrong-remainder-probe hole
        raise RuntimeError(
            "padded-carry path resumed from a checkpoint (start_step="
            f"{start_step}) — the compile guard probed the wrong remainder; "
            "route resumes through the owned-state path")
    seed, advance, crop = make_padded_carry_machinery(cfg, mesh)
    Tp = seed(T_owned)
    del T_owned  # unpin the owned-field device buffer for the solve
    res = drive(cfg.with_(report_sum=False), Tp, advance,
                start_step=start_step, fetch=False, warm_exec=warm_exec,
                two_point_repeats=two_point_repeats, precompiled=pre,
                precompile_s=guard.probe_s)
    res.guard = (guard if guard.probed or guard.degraded else None)  # a
    # peer-agreed degrade with a local budget of 0 still must be visible
    return _finalize_carried(cfg, res, crop, fetch)


def fuse_depth_sharded(cfg: HeatConfig, axis_sizes) -> int:
    """Halo width per exchange: requested fuse depth capped by the smallest
    local extent (a shard can't lend deeper halo than it owns) and by the
    local kernel's per-pass fusion cap for the rank.

    Auto depth balances the k-dependent costs per owned point-step:
    per-exchange overhead (~1/k per step — on the default padded-carry
    path that is the collective dispatch + the exchange breaking kernel
    fusion, no longer a pad+crop copy) against redundant margin work
    growing as ~2*d*k/L — minimized at k* = sqrt(L/d), then capped at
    the local KERNEL's per-pass chunk depth in BOTH ranks: fusing deeper
    than the kernel consumes per pass saves only collective dispatches
    (the HBM passes don't amortize further) while still paying 2*d*k
    margin compute on wider ghosts.

    The 2D cap is round-5 MEASURED, not just modeled: the round-2 sweep
    that crowned k=32 (k=8/16/32 -> 94/98/112% roofline) predates the
    round-4 ``_thin_chunk_cap``, which executes k=32 as two 16-deep
    passes at flagship width; with that cap in place the on-chip 4-point
    curve (benchmarks/collective_overhead.json, 2026-08-01) inverts the
    optimum: k=16 -> 1.571e11, k=32 -> 1.399e11 (12% loss) at 16384^2
    f32. 3D clamps at _KMAX_3D (=8) for the same reason (for realistic
    3D shards sqrt(L/d) <= 8 anyway: 512^3 over 2x2x2 gives k*=9->8).
    An EXPLICIT fuse_steps is honored either way (capped only by the
    local extent) — the A/B labs must be able to pin any depth — and a
    CONFIGURED xla local kernel has no per-pass chunk, so its auto depth
    keeps the plain sqrt form (including dtype float64, which can never
    run the Pallas kernel and always resolves to xla). The cap keys on
    the configured kernel deliberately: local_kernel='auto' keeps the
    cap even on hosts where auto resolves to xla at runtime (CPU tests,
    the 8-device dryrun), so chipless runs exercise the same exchange
    structure the TPU default compiles — structural fidelity over a
    perf optimum no one measures off-chip."""
    from ..ops.pallas_stencil import _KMAX_3D

    kmax = _KMAX_2D if cfg.ndim == 2 else _KMAX_3D
    local_min = min(cfg.n // s for s in axis_sizes)
    want = cfg.fuse_steps
    if not want:
        want = max(1, min(kmax, round((local_min / cfg.ndim) ** 0.5)))
        if (cfg.ndim == 2 and cfg.local_kernel != "xla"
                and cfg.dtype != "float64"):
            want = min(want, _auto_chunk_2d(cfg, axis_sizes))
    return max(1, min(want, local_min))


def _auto_chunk_2d(cfg: HeatConfig, axis_sizes) -> int:
    """Per-pass chunk depth of the 2D kernel the planner will SELECT for
    this shard, evaluated at the ghost-PADDED shape the kernel actually
    sees (deepest candidate ghost allowance — near the band threshold
    the unpadded width under-reports: local 4864 reads cap=32 unpadded
    but the (4864+64)-wide runtime array chunks at 16). Sole consumer:
    ``fuse_depth_sharded``'s depth cap. (A round-5 interim also fed a
    guard wide-band signal from here; the guard reverted to depth-only
    gating once the probe's topology-compile cost was measured — see
    ``_guard_fuse_compile``.)"""
    from ..ops.pallas_stencil import effective_chunk_2d

    rows = cfg.n // axis_sizes[0] + 2 * _KMAX_2D
    cols = cfg.n // axis_sizes[-1] + 2 * _KMAX_2D
    return effective_chunk_2d((rows, cols), cfg.dtype)


def _chunked_advance(mesh, step, kf: int):
    """Jitted, donated k-step advance: fused blocks of ``kf`` steps + one
    remainder call, via ``step(local_state, nsteps)`` inside shard_map —
    the ONE chunking scheme both the owned-state and padded-carry paths
    use (only the step callable differs)."""
    spec = P(*mesh.axis_names)

    @functools.partial(jax.jit, static_argnums=1, donate_argnums=0)
    def advance(Tg, k: int):
        def body(local):
            n_fused, rem = divmod(k, kf)
            if n_fused:
                local = jax.lax.fori_loop(
                    0, n_fused, lambda i, t: step(t, kf), local)
            if rem:
                local = step(local, rem)
            return local

        return shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec,
                         check_vma=False)(Tg)

    return advance


def make_advance(cfg: HeatConfig, mesh):
    local_multi, _ = make_local_multistep(
        cfg, mesh.axis_names, mesh.devices.shape)
    kf = fuse_depth_sharded(cfg, mesh.devices.shape)
    return _chunked_advance(mesh, local_multi, kf)


def make_padded_carry_machinery(cfg: HeatConfig, mesh):
    """(seed, advance, crop) carrying the PADDED field as solve state.

    The classic advance pays a pad+crop copy of every local block per
    exchange (~2/k full-field HBM passes at fuse depth k). Carrying each
    shard as owned+2w cells removes both copies: every fused block is
    exchange-in-place + kernel, ghosts garbage between exchanges but
    rewritten before any read. Owned-cell values are bit-identical to the
    classic path (same exchange, same kernel, same bounds). The same
    padded-state idea the parity machinery uses for w=1 ghosts
    (make_parity_machinery), here at the communication-avoiding width.
    """
    axis_names = mesh.axis_names
    axis_sizes = mesh.devices.shape
    _, padded_multi = make_local_multistep(cfg, axis_names, axis_sizes)
    kf = fuse_depth_sharded(cfg, axis_sizes)
    bc_value = cfg.bc_value
    spec = P(*axis_names)
    smap = functools.partial(shard_map, mesh=mesh, in_specs=(spec,),
                             out_specs=spec, check_vma=False)

    def seed(T_owned: jax.Array) -> jax.Array:
        # the caller must drop its T_owned reference after seeding (see
        # _solve_padded_carry): the owned-field buffer (1 GiB at 16384^2
        # f32) must not stay pinned for the whole solve alongside the
        # padded state. (Donation can't help here — the padded output is a
        # different shape, so the input buffer is never reusable and JAX
        # warns.)
        return jax.jit(smap(lambda local: halo_pad(local, bc_value, kf)))(
            T_owned)

    # margins stay width kf across calls; only the step count shrinks on
    # the remainder chunk
    advance = _chunked_advance(mesh, lambda p, k: padded_multi(p, kf, k), kf)

    @jax.jit
    def crop(Tp):
        return smap(
            lambda p: p[tuple(slice(kf, -kf) for _ in range(p.ndim))])(Tp)

    return seed, advance, crop


def make_mega_machinery(cfg: HeatConfig, mesh):
    """(seed, advance, crop, kf): the padded-carry machinery wrapped in the
    SERVE dispatch contract (serve/engine.py MegaLaneEngine) — one request
    spanning the whole device mesh as a *mega-lane*.

    ``advance(Tp, rem, k)`` runs ``k`` steps of the exact chunked body the
    solo sharded ``drive()`` compiles (``divmod(k, kf)`` fused blocks of
    the communication-avoiding ``padded_multi`` plus one remainder block —
    owned-cell values are bit-identical under ANY chunk partition, the
    same margin argument that makes fused exchanges bit-identical to
    every-step exchanges) and returns ``(Tp', rem', boundary)``:

    - ``Tp`` is donated (the solo drive's double-buffer ping-pong);
    - ``rem`` is an undonated ``(1,)`` int32 countdown — ``rem' =
      max(rem - k, 0)``, the same algebra the lane engine's per-lane
      countdown produces, so the scheduler's host mirror predicts it;
    - ``boundary`` is the ``(K_BOUNDARY, 1)`` int32 vector of
      [remaining; isfinite; bitcast numerics stats] the serve
      scheduler's boundary fetch expects (serve/engine.BOUNDARY_ROWS) —
      the finite bit and the stats reduced over OWNED cells only (each
      shard contributes its interior verdict through the same shard_map
      program; the garbage ghost margins between exchanges never vote),
      so mega-lane health AND solution quality ride the boundary D2H
      exactly like a packed lane's. The chunk's final step runs as its
      own fused block so the pre-step owned cells are in scope for the
      residual stat — owned-cell invariance under chunk partitioning
      (the margin argument above) keeps the field bytes unchanged.

    ``seed``/``crop`` are the padded-carry entry/exit programs, returned
    un-jit-called so the serve engine can AOT-compile them once per
    (config, mesh) and reuse across admissions."""
    axis_names = mesh.axis_names
    axis_sizes = mesh.devices.shape
    _, padded_multi = make_local_multistep(cfg, axis_names, axis_sizes)
    kf = fuse_depth_sharded(cfg, axis_sizes)
    bc_value = cfg.bc_value
    nd = cfg.ndim
    spec = P(*axis_names)
    smap = functools.partial(shard_map, mesh=mesh, in_specs=(spec,),
                             check_vma=False)

    seed = jax.jit(smap(lambda local: halo_pad(local, bc_value, kf),
                        out_specs=spec))

    from ..serve.engine import pack_boundary

    @functools.partial(jax.jit, static_argnums=2, donate_argnums=0)
    def advance(Tp, rem, k: int):
        def body(padded):
            if k > 1:
                n_fused, r_ = divmod(k - 1, kf)
                if n_fused:
                    padded = jax.lax.fori_loop(
                        0, n_fused, lambda i, t: padded_multi(t, kf, kf),
                        padded)
                if r_:
                    padded = padded_multi(padded, kf, r_)
            prev = padded
            # the chunk's final step is its own fused block so the
            # pre-step owned cells feed the residual stat; owned cells
            # are invariant under chunk partitioning, so the field
            # bytes match the one-shot chunk body exactly
            padded = padded_multi(padded, kf, 1)
            ctr = tuple(slice(kf, -kf) for _ in range(nd))
            # per-shard owned-interior health bit + numerics stats:
            # reading only (never writing) the stepped state, so
            # bit-identity is untouched — the PR-5 lane-engine
            # argument, one mesh wide
            own = padded[ctr].astype(jnp.float32)
            one = (1,) * nd
            fin = jnp.isfinite(padded[ctr]).all().reshape(one)
            resid = jnp.abs(own - prev[ctr].astype(jnp.float32)
                            ).max().reshape(one)
            return (padded, fin, resid, own.min().reshape(one),
                    own.max().reshape(one), own.sum().reshape(one))

        Tp, fins, resid, tmin, tmax, heat = shard_map(
            body, mesh=mesh, in_specs=(spec,), out_specs=(spec,) * 6,
            check_vma=False)(Tp)
        rem2 = jnp.maximum(rem - k, 0)
        finite = jnp.all(fins).astype(rem2.dtype).reshape((1,))
        # cross-shard merge: max/min/max/sum over the per-shard partials
        stats = jnp.stack([resid.max(), tmin.min(), tmax.max(),
                           heat.sum()]).astype(jnp.float32).reshape(4, 1)
        return Tp, rem2, pack_boundary(rem2, finite, stats)

    crop = jax.jit(smap(
        lambda p: p[tuple(slice(kf, -kf) for _ in range(nd))],
        out_specs=spec))
    return seed, advance, crop, kf


@register("sharded")
def solve(cfg: HeatConfig, T0: Optional[np.ndarray] = None, mesh=None,
          fetch: bool = True, warm_exec: bool = False,
          two_point_repeats: int = 0, **_) -> SolveResult:
    mesh = mesh or build_mesh(cfg.ndim, cfg.mesh_shape)
    validate_divisible(cfg.n, mesh)
    master_print(f"Automatic mesh decomposition: {dict(zip(mesh.axis_names, mesh.devices.shape))}")
    # local block dims + shard->device binding, the reference's per-rank
    # announcements (local nx/ny at mpi+cuda/heat.F90:239-240, rank->GPU at
    # :69), gated master-only like every other stdout line
    local = tuple(cfg.n // s for s in mesh.devices.shape)
    master_print("local block: " + " x ".join(str(v) for v in local))
    flat = list(np.ndenumerate(mesh.devices))
    for coords_d, dev in flat[:32]:
        master_print(f"  mesh {coords_d} -> device {dev.id} "
                     f"(process {getattr(dev, 'process_index', 0)})")
    if len(flat) > 32:
        master_print(f"  ... ({len(flat) - 32} more shards)")

    if cfg.checkpoint_every:
        # announce the I/O contract up front, like the mesh decomposition:
        # on a multi-host job the async writer persists each process's own
        # shards (checkpoint.save_shards) from a device-side snapshot while
        # stepping continues — same snapshot-and-continue contract as the
        # single-host global dump
        master_print("checkpoint I/O: "
                     + ("async snapshot-and-continue (bounded queue depth "
                        "2; --async-io off for the sync fallback)"
                        if cfg.use_async_io() else "sync (--async-io off)"))
    if cfg.parity_order:
        res = _solve_parity(cfg, T0, mesh, fetch, warm_exec)
    elif not cfg.checkpoint_every and not cfg.check_numerics and cfg.ntime:
        # default fast path: padded-carry state (no per-exchange pad+crop
        # copies). Checkpoint/numerics runs keep the owned-state path:
        # their boundary events need the OWNED field (a padded-state
        # snapshot would persist garbage ghost margins), which padded
        # state only yields via a crop. The events themselves no longer
        # stall that path — drive's async pipeline snapshots on device
        # and resumes stepping (runtime/async_io.py) — so what the owned
        # path still pays vs this one is only the per-exchange pad+crop
        # copies, not the D2H+disk wall time.
        res = _solve_padded_carry(cfg, T0, mesh, fetch, warm_exec,
                                  two_point_repeats)
    else:
        # owned-state path (checkpoint / numerics runs): same auto fuse
        # depth, same deep-unroll kernel — guard it too, with the probe
        # compiling THIS path's program (owned global shape, and a
        # remaining count that respects checkpoint resume)
        sharding = NamedSharding(mesh, P(*mesh.axis_names))
        T, start_step = resolve_initial_field(cfg, T0, sharding=sharding)
        cfg, pre, guard = _guard_fuse_compile(
            cfg, mesh, cfg.ntime - start_step, padded=False)
        res = drive(cfg, T, make_advance(cfg, mesh), start_step=start_step,
                    fetch=fetch, warm_exec=warm_exec,
                    two_point_repeats=two_point_repeats, precompiled=pre,
                    precompile_s=guard.probe_s)
        res.guard = (guard if guard.probed or guard.degraded else None)  # a
    # peer-agreed degrade with a local budget of 0 still must be visible
    res.mesh_shape = tuple(mesh.devices.shape)
    res.mesh = mesh
    return res
