"""Killable compile-guard probe (child process).

Round-4's guard ran its probe in a daemon THREAD: a timed-out Mosaic
compile could not be cancelled (C++ holds the GIL-released core) and kept
burning a core — possibly >36 min for the thin-band deep-unroll wedge —
polluting the very bench row the fallback solve was producing (VERDICT r4
next #8). This child process is the fix: it performs the same
``_compile_probe`` AOT compiles *chiplessly* via
``jax.experimental.topologies`` (the Mosaic + XLA:TPU compilers ship in
libtpu and need no device — the round-4 compile-lab machinery), then
ships the executables back to the parent through
``jax.experimental.serialize_executable``. On budget expiry the parent
SIGKILLs this process group and the orphan compile dies with it.

Spec protocol (argv[1] = JSON file):
  cfg:        dataclasses.asdict(HeatConfig)
  mesh_shape: list[int]     — parent mesh axis sizes
  axis_names: list[str]
  kf / remaining / padded   — forwarded to _compile_probe
  platform:   "tpu" | "cpu" — parent's default backend
  chip:       "v5e" | ...   — machine.classify name (tpu only)
  out:        path for the pickled {k: serialized-executable} result

Exit codes: 0 = result written; anything else = probe failed (the parent
falls back to the in-thread probe — e.g. when another process holds the
libtpu lockfile, a single-resource constraint the thread path never hits).
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import sys


# device counts the probe knows how to spell as a physical topology; the
# serialized executable's device assignment must match the parent's
# device count, not its logical mesh shape
_TOPO_BY_NDEV = {1: "1x1", 2: "1x2", 4: "2x2", 8: "2x4", 16: "4x4"}


def topology_name(chip: str, ndev: int) -> str | None:
    dims = _TOPO_BY_NDEV.get(ndev)
    return f"{chip}:{dims}" if dims else None


def main() -> int:
    spec = json.loads(open(sys.argv[1]).read())

    import jax

    jax.config.update("jax_platforms", "cpu")  # chipless by construction

    from jax.experimental import serialize_executable

    from ..config import HeatConfig
    from .sharded import _compile_probe

    cfg = HeatConfig(**spec["cfg"])
    mesh_shape = tuple(spec["mesh_shape"])
    axis_names = tuple(spec["axis_names"])
    ndev = 1
    for s in mesh_shape:
        ndev *= s

    if spec["platform"] == "tpu":
        from jax.experimental import topologies

        from .. import machine
        from ..ops.pallas_stencil import force_compiled_kernels

        if not os.environ.get("HEAT_CHIP_CALIBRATION"):
            # this forced-CPU process would otherwise plan with
            # machine._DEFAULT (v5e) geometry/VMEM ceilings — on a
            # v5p/v6e parent that compiles a program the parent's planner
            # would never pick. A calibration env (inherited) wins, as it
            # does in the parent.
            machine.override(spec["chip"])
        name = topology_name(spec["chip"], ndev)
        if name is None:
            print(f"no topology spelling for {ndev} devices", file=sys.stderr)
            return 3
        topo = topologies.get_topology_desc(name, "tpu")
        mesh = topologies.make_mesh(topo, mesh_shape, axis_names)
        ctx = force_compiled_kernels()
    else:  # cpu parent (tests): same-platform compile, no topology needed
        import numpy as np
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < ndev:
            print(f"need {ndev} cpu devices, have {len(devs)} — set "
                  f"XLA_FLAGS=--xla_force_host_platform_device_count",
                  file=sys.stderr)
            return 3
        mesh = Mesh(np.array(devs[:ndev]).reshape(mesh_shape), axis_names)
        ctx = contextlib.nullcontext()

    with ctx:
        pre = _compile_probe(cfg, mesh, spec["kf"], spec["remaining"],
                             spec["padded"])
        payloads = {k: serialize_executable.serialize(c)
                    for k, c in pre.items()}

    tmp = spec["out"] + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(payloads, f)
    os.replace(tmp, spec["out"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
