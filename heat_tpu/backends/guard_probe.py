"""Killable compile-guard probe (child process).

Round-4's guard ran its probe in a daemon THREAD: a timed-out Mosaic
compile could not be cancelled (C++ holds the GIL-released core) and kept
burning a core — possibly >36 min for the thin-band deep-unroll wedge —
polluting the very bench row the fallback solve was producing (VERDICT r4
next #8). This child process is the fix: it performs the same
``_compile_probe`` AOT compiles *chiplessly* via
``jax.experimental.topologies`` (the Mosaic + XLA:TPU compilers ship in
libtpu and need no device — the round-4 compile-lab machinery), then
ships the executables back to the parent through
``jax.experimental.serialize_executable``. On budget expiry the parent
SIGKILLs this process group and the orphan compile dies with it.

Spec protocol (argv[1] = JSON file):
  cfg:        dataclasses.asdict(HeatConfig)
  mesh_shape: list[int]     — parent mesh axis sizes
  axis_names: list[str]
  kf / remaining / padded   — forwarded to _compile_probe
  platform:   "tpu" | "cpu" — parent's default backend
  chip:       "v5e" | ...   — machine.classify name (tpu only)
  out:        path for the pickled {k: serialized-executable} result

Exit codes: 0 = result written; anything else = probe failed (the parent
falls back to the in-thread probe — e.g. when another process holds the
libtpu lockfile, a single-resource constraint the thread path never hits).
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import sys


# Device counts the probe knows how to spell as a physical topology; the
# serialized executable's device assignment must match the parent's
# device count, not its logical mesh shape. The map is PER CHIP, not a
# flat ndev->layout table, because (validated against the attached
# libtpu, first on-chip window of round 5):
#   * v5e/v6e topologies are 2-D, v4/v5p are 3-D — "v5p:2x4" is an
#     invalid spelling, the old flat table's entry never worked;
#   * the default chips_per_host_bounds is 2x2x1, so any sub-host
#     layout ("v5e:1x1" — the single-chip BENCH path) is rejected as
#     "not divisible" unless the bounds are overridden. The override
#     must be a plain int list: the PJRT option is typed, and a string
#     form fails with INVALID_ARGUMENT (observed in sweep_r5.log);
#   * v4 exposes TWO TensorCore devices per chip here (1x1x1 -> 2
#     devices), so its entries are keyed by even device counts only.
def _subhost(*bounds: int) -> dict:
    return {"chips_per_host_bounds": list(bounds)}


# v6e aliases the v5e table (same 2-D spellings and host bounds) so a
# future spelling fix cannot drift between them
_V5E_LIKE = {1: ("1x1", _subhost(1, 1, 1)), 2: ("1x2", _subhost(1, 2, 1)),
             4: ("2x2", {}), 8: ("2x4", {}), 16: ("4x4", {})}

_TOPO_BY_CHIP: dict[str, dict[int, tuple[str, dict]]] = {
    "v5e": _V5E_LIKE,
    "v6e": _V5E_LIKE,
    "v5p": {1: ("1x1x1", _subhost(1, 1, 1)), 2: ("1x2x1", _subhost(1, 2, 1)),
            4: ("2x2x1", {}), 8: ("2x2x2", {}), 16: ("2x2x4", {})},
    "v4":  {2: ("1x1x1", _subhost(1, 1, 1)), 4: ("1x2x1", _subhost(1, 2, 1)),
            8: ("2x2x1", {}), 16: ("2x2x2", {})},
}


def topology_spec(chip: str, ndev: int) -> tuple[str, dict] | None:
    """(topology_name, get_topology_desc kwargs) for ``ndev`` parent
    devices on ``chip``, or None when there is no spelling (the child
    exits 3 and the parent falls back to the in-thread probe)."""
    entry = _TOPO_BY_CHIP.get(chip, {}).get(ndev)
    if entry is None:
        return None
    name, kwargs = entry
    return f"{chip}:{name}", kwargs


def main() -> int:
    spec = json.loads(open(sys.argv[1]).read())

    import jax

    jax.config.update("jax_platforms", "cpu")  # chipless by construction

    from jax.experimental import serialize_executable

    from ..config import HeatConfig
    from .sharded import _compile_probe

    cfg = HeatConfig(**spec["cfg"])
    mesh_shape = tuple(spec["mesh_shape"])
    axis_names = tuple(spec["axis_names"])
    ndev = 1
    for s in mesh_shape:
        ndev *= s

    if spec["platform"] == "tpu":
        from jax.experimental import topologies

        from .. import machine
        from ..ops.pallas_stencil import force_compiled_kernels

        if not os.environ.get("HEAT_CHIP_CALIBRATION"):
            # this forced-CPU process would otherwise plan with
            # machine._DEFAULT (v5e) geometry/VMEM ceilings — on a
            # v5p/v6e parent that compiles a program the parent's planner
            # would never pick. A calibration env (inherited) wins, as it
            # does in the parent.
            machine.override(spec["chip"])
        topo_spec = topology_spec(spec["chip"], ndev)
        if topo_spec is None:
            print(f"no topology spelling for {ndev} {spec['chip']} devices",
                  file=sys.stderr)
            return 3
        name, topo_kwargs = topo_spec
        topo = topologies.get_topology_desc(name, "tpu", **topo_kwargs)
        if len(topo.devices) != ndev:
            # devices-per-chip drifted (libtpu version / chip config) —
            # an executable built here could not load in the parent
            print(f"topology {name} has {len(topo.devices)} devices, "
                  f"parent has {ndev}", file=sys.stderr)
            return 3
        mesh = topologies.make_mesh(topo, mesh_shape, axis_names)
        ctx = force_compiled_kernels()
    else:  # cpu parent (tests): same-platform compile, no topology needed
        import numpy as np
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < ndev:
            print(f"need {ndev} cpu devices, have {len(devs)} — set "
                  f"XLA_FLAGS=--xla_force_host_platform_device_count",
                  file=sys.stderr)
            return 3
        mesh = Mesh(np.array(devs[:ndev]).reshape(mesh_shape), axis_names)
        ctx = contextlib.nullcontext()

    with ctx:
        pre = _compile_probe(cfg, mesh, spec["kf"], spec["remaining"],
                             spec["padded"])
        payloads = {k: serialize_executable.serialize(c)
                    for k, c in pre.items()}

    tmp = spec["out"] + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(payloads, f)
    os.replace(tmp, spec["out"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
