"""Shared device-side solve driver: chunked jitted loop with heartbeat,
checkpointing, and timing.

The reference's hot loop is a host loop launching one kernel per step
(fortran/cuda_kernel/heat.F90:30-34). On TPU we instead compile a whole
*chunk* of steps into one ``lax.fori_loop`` program and call it repeatedly —
host involvement only at heartbeat/checkpoint boundaries, with the T/T_old
double buffer donated so XLA ping-pongs buffers with zero copies (replacing
the per-step ``T_old_d = T_d`` device memcpy at fortran/cuda_kernel/heat.F90:32).
"""

from __future__ import annotations

import math
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import HeatConfig
from ..runtime import async_io, checkpoint, debug, faults
from ..runtime import trace as trace_mod
from ..runtime.logging import master_print
from ..runtime.timing import Timing, sync, two_point_rate
from . import SolveResult


# --on-nan rollback: how many times the same flagged step may be retried
# before the blow-up is declared deterministic (a genuine CFL violation
# reproduces identically; a soft-error/injected NaN does not).
_MAX_ROLLBACKS_PER_STEP = 2


def _addressable(x) -> bool:
    """True when every shard of x lives on this process's devices.

    Injectable seam (tests fake a multi-host world by patching this): in a
    real multi-host job a mesh-sharded global array is NOT fully
    addressable and ``np.asarray`` on it raises — the same case
    ``timing.sync`` already guards."""
    return not isinstance(x, jax.Array) or x.is_fully_addressable


def host_fetch(x):
    """Fetch to host, or None when the array spans other processes (the
    caller must then use per-shard paths: ``io.write_soln_sharded``,
    ``checkpoint.save_shards``)."""
    return np.asarray(x) if _addressable(x) else None


def event_interval(cfg: HeatConfig) -> int:
    """Steps per device program: gcd of the host-visible event intervals."""
    ivals = [v for v in (cfg.heartbeat_every, cfg.checkpoint_every) if v > 0]
    if not ivals:
        return max(cfg.ntime, 1)
    g = ivals[0]
    for v in ivals[1:]:
        g = math.gcd(g, v)
    return g


def chunk_sizes(cfg: HeatConfig, remaining: int) -> list[int]:
    """Every step-count the drive loop will call ``advance`` with (at most
    two: the steady chunk and a final remainder). The ONE derivation both
    ``drive``'s warmup and the sharded compile guard's probe use — the
    guard must bound every program drive will compile, remainder included
    (a k=100 remainder still unrolls the same deep-fused kernel and is a
    distinct XLA program)."""
    if remaining <= 0:
        return []
    k0 = min(event_interval(cfg), remaining)
    sizes = {k0}
    if remaining % k0:
        sizes.add(remaining % k0)
    return sorted(sizes)


def aot_compile_chunks(advance, example, sizes, compiled=None, label=None,
                       kernel=None):
    """AOT-compile ``advance(example..., k)`` for every chunk size ``k``
    in ``sizes`` not already covered; returns ``(compiled, seconds)``.

    The ONE compile path for chunked step programs: ``drive``'s warmup and
    the serving engine's lane programs (serve/engine.py) both go through
    here, so no compile ever lands inside a timed region and compile
    bookkeeping (guard hand-off, serve's one-per-bucket accounting) stays a
    dict of size -> executable everywhere. Being the one path also makes
    it the compile observatory's tap (runtime/prof.py): every program
    actually built lands in the process-wide structured compile log with
    its ``label`` (caller-supplied key: bucket/tier for lanes, grid/dtype
    for solo solves), per-program wall, and first-vs-warm — the wall of a
    warm re-compile is the persistent compile cache's report card.

    ``example`` is a single array for the solo drive shape
    (``advance(T, k)``) or a TUPLE of arrays for multi-argument programs
    (the serve engine's ``advance(fields, r, n, remaining, k)``, which
    also returns the per-lane ``(2, L)`` boundary vector of remaining
    steps + isfinite bits — its leaves are donated selectively, which a
    single pytree argument cannot express); a tuple is splatted into
    ``lower``.

    ``kernel`` names the stepping body when one label can cover several
    (the serve lane programs compile both the XLA oracle and the Pallas
    lane kernels for the same bucket/tier — the compile log must tell
    them apart, or a Pallas-vs-XLA A/B reads as one warm cache key).
    """
    from ..runtime import prof

    compiled = dict(compiled or {})
    args = example if isinstance(example, tuple) else (example,)
    if label is None:
        shape = getattr(args[0], "shape", ())
        dtype = getattr(args[0], "dtype", "?")
        label = f"chunk {tuple(shape)} {dtype}"
    if kernel is not None:
        label = f"{label} [{kernel}]"
    t0 = time.perf_counter()
    for k in sizes:
        if k not in compiled:
            tk = time.perf_counter()
            compiled[k] = advance.lower(*args, k).compile()
            prof.compile_log().note(label, k, time.perf_counter() - tk)
    return compiled, time.perf_counter() - t0


def solo_program_specs():
    """Program-registry seam (ISSUE 13): the solo drive's chunked advance
    families as abstract ProgramSpecs — `heat-tpu audit` traces/lowers
    them on shape structs to check donation (the T/T_old double-buffer
    ping-pong this module's docstring promises), purity, dtype
    discipline, and digest drift, without running a solve."""
    from ..analysis.programs import ProgramSpec
    from ..utils import jnp_dtype

    def _spec(ndim, n, dtype, bc, steps=8):
        def build():
            from .xla import make_advance

            cfg = HeatConfig(n=n, ndim=ndim, dtype=dtype, bc=bc,
                             ntime=steps, backend="xla")
            adv = make_advance(cfg)
            T = jax.ShapeDtypeStruct(cfg.shape, jnp_dtype(dtype))
            return adv, (T, steps), (1,)

        return ProgramSpec(
            name=f"solo/xla/{ndim}d/n{n}/{dtype}/{bc}", build=build,
            donated=(0,), dtype=dtype,
            storage_round=(dtype == "bfloat16"), steps=steps,
            kernel="xla", family="solo")

    return [
        _spec(2, 48, "float32", "edges"),
        _spec(2, 48, "float32", "ghost"),
        _spec(2, 48, "float32", "periodic"),
        _spec(2, 48, "bfloat16", "edges"),
        _spec(2, 48, "float64", "ghost"),
        _spec(3, 16, "float32", "ghost"),
    ]


def drive(
    cfg: HeatConfig,
    T_dev: jax.Array,
    advance: Callable[[jax.Array, int], jax.Array],
    start_step: int = 0,
    to_host: Callable[[jax.Array], Optional[np.ndarray]] = host_fetch,
    warmup: bool = True,
    fetch: bool = True,
    warm_exec: bool = False,
    two_point_repeats: int = 0,
    precompiled: Optional[dict] = None,
    precompile_s: float = 0.0,
) -> SolveResult:
    """Run ``advance(T, k)`` (jitted, static k, donated T) to ``cfg.ntime``.

    Host-visible events (checkpoints, numerics flags) run through the
    asynchronous I/O pipeline by default (``cfg.async_io``,
    runtime/async_io.py): a checkpoint boundary costs one on-device buffer
    copy and stepping resumes immediately, with the D2H transfer and
    atomic-rename write in a bounded-queue background writer —
    backpressure (queue depth 2), drain on every exit path (no snapshot
    silently dropped), writer errors surfaced at the next boundary.
    ``--async-io off`` restores the inline sync->fetch->save stall.

    ``two_point_repeats > 0`` additionally measures the overhead-corrected
    two-point rate (``timing.two_point_rate`` — the headline benchmark's
    protocol) on a COPY of the final state, so the solve result is
    untouched; costs one extra buffer pair and 1 + 3*repeats extra chunk
    executions (warm + per-repeat single + back-to-back pair) — for
    benchmark configs the chunk is the whole solve, so budget device time
    accordingly.

    ``precompiled`` maps chunk size -> an already-compiled executable for
    ``advance`` (the sharded compile guard hands its probe's work forward
    so a guarded solve never compiles the same program twice); sizes it
    covers are skipped in warmup. ``precompile_s`` is the wall time the
    caller already spent producing them — folded into ``compile_s`` and
    ``total_s`` so guard minutes never vanish from the reported timing."""
    t_all0 = time.perf_counter()
    chunk = event_interval(cfg)
    remaining = cfg.ntime - start_step
    # request-scoped tracing (runtime/trace.py): the solo path records
    # into the process-global ring so `heat-tpu run --trace` puts chunk
    # dispatches, checkpoint snapshots, and the background writer's
    # D2H+publish spans (the PR-1 overlap) on one Perfetto timeline.
    tracer = trace_mod.get_tracer()
    drv_track = tracer.thread_track("solve") if tracer.enabled else None

    # AOT-compile every chunk size the loop will encounter (at most two: the
    # steady chunk and a final remainder) so no compile lands inside the
    # timed region and no throwaway compute runs. Analogous to PyCUDA's
    # up-front nvcc JIT (python/cuda/cuda.py:86).
    compile_s = precompile_s
    compiled = dict(precompiled or {})
    if warmup and remaining > 0:
        t_c0 = time.perf_counter()
        compiled, spent = aot_compile_chunks(
            advance, T_dev, chunk_sizes(cfg, remaining), compiled,
            label=f"solve {cfg.backend} n{cfg.n}^{cfg.ndim} {cfg.dtype}")
        if tracer.enabled and spent > 0:
            tracer.complete("compile", drv_track, t_c0, cat="solve",
                            args={"sizes": chunk_sizes(cfg, remaining)})
        compile_s += spent
        t0 = time.perf_counter()
        if warm_exec:
            # benchmark mode: one throwaway execution on a copy (donation
            # safety) so first-run runtime initialization — which can be tens
            # of seconds on a tunneled platform and happens lazily, after
            # .compile() — lands here, not in the timed region
            k0 = min(chunk, remaining)
            sync(compiled[k0](jnp.copy(T_dev)))
        compile_s += time.perf_counter() - t0

    t0 = time.perf_counter()
    step = start_step
    # Async I/O pipeline (the default): checkpoint boundaries cost one
    # on-device buffer copy — the D2H fetch and atomic disk write happen in
    # a bounded-queue background writer while the device keeps stepping —
    # and check_numerics becomes a device-side flag posted at each boundary
    # and fetched at the NEXT one (by which point it computed behind the
    # following chunk). --async-io off restores the reference-shaped
    # sync(T_dev) -> fetch -> save stall below, unchanged.
    async_on = cfg.use_async_io() and bool(cfg.checkpoint_every
                                           or cfg.check_numerics)
    writer = (async_io.SnapshotWriter(tracer=tracer)
              if async_on and cfg.checkpoint_every else None)
    # pending boundary flag from the async numerics leg:
    # (device scalar, step, snapshot-or-None, deferred-checkpoint?)
    pending_flag = None
    # Fault-injection plan (runtime/faults.py): None in every normal run —
    # the loop below then touches nothing fault-related beyond one
    # ``is not None`` test per boundary.
    plan = faults.plan_for(cfg)
    # --on-nan rollback: hold one device snapshot of the newest boundary
    # whose finite flag PASSED; a flagged boundary restores it and re-steps
    # instead of aborting. Deterministic blow-ups re-flag at the same step
    # and abort after _MAX_ROLLBACKS_PER_STEP — only transient faults
    # (soft-error bit flips, injected NaN) actually recover. Costs one
    # device-side copy per boundary, paid ONLY when the mode is on.
    rollback = cfg.on_nan == "rollback" and cfg.check_numerics
    # seed with the starting state so even a first-chunk transient recovers
    last_good = ((async_io.device_snapshot(T_dev), step) if rollback
                 else None)      # (snapshot, step), verified finite
    rollbacks_at: dict = {}      # step -> rollbacks consumed there

    def _submit_snapshot(T_snap, at_step: int) -> None:
        check = cfg.check_numerics
        if tracer.enabled:
            tracer.instant("checkpoint-snapshot", drv_track, cat="solve",
                           args={"step": at_step})

        def job():
            T_ck = to_host(T_snap)  # D2H lands HERE, in the writer thread
            if check:
                # sync mode checks the chunk before saving its boundary;
                # async detects one boundary late (pending_flag), so the
                # writer re-validates the snapshot it is about to persist —
                # a non-finite field never reaches disk on either path
                debug.check_finite(T_ck if T_ck is not None else T_snap,
                                   at_step, label="checkpoint snapshot")
            if T_ck is not None:
                checkpoint.save(cfg, T_ck, at_step)
            else:  # multi-host: each process persists its own shards
                checkpoint.save_shards(cfg, T_snap, at_step)

        job._trace = (f"checkpoint @{at_step}", None)
        writer.submit(job)

    def _try_rollback(bad_step: int) -> bool:
        """Restore the last verified-finite boundary after a flagged one;
        False -> no rollback possible/allowed, the caller re-raises."""
        nonlocal T_dev, step
        if not rollback or last_good is None:
            return False
        n = rollbacks_at.get(bad_step, 0)
        if n >= _MAX_ROLLBACKS_PER_STEP:
            master_print(f"on-nan rollback: step {bad_step} flagged again "
                         f"after {n} rollbacks — deterministic blow-up, "
                         f"aborting")
            return False
        rollbacks_at[bad_step] = n + 1
        snap, good = last_good
        master_print(f"on-nan rollback: non-finite field at step {bad_step}; "
                     f"rolling back to verified boundary {good} "
                     f"(attempt {n + 1}/{_MAX_ROLLBACKS_PER_STEP})")
        # copy the snapshot back in: the restored buffer is donated into the
        # next advance, but last_good must stay restorable for a second try
        T_dev = async_io.device_snapshot(snap)
        step = good
        return True

    def _settle_pending() -> bool:
        """Async mode: judge the boundary flag posted one chunk ago. True ->
        it flagged and we rolled back (caller continues stepping). On a
        pass, promotes the boundary snapshot to last_good and performs its
        deferred checkpoint submit (rollback mode defers persistence until
        the flag verdict so a NaN snapshot never races the writer)."""
        nonlocal pending_flag, last_good
        flag, fstep, snap, is_ckpt = pending_flag
        pending_flag = None
        try:
            debug.raise_if_flagged(flag, fstep)
        except FloatingPointError:
            if _try_rollback(fstep):
                return True
            raise
        if rollback:
            last_good = (snap, fstep)
            if is_ckpt:
                _submit_snapshot(snap, fstep)
        return False

    try:
        with debug.maybe_profile(cfg.profile_dir):
            while True:
                while step < cfg.ntime:
                    k = min(chunk, cfg.ntime - step)
                    fn = compiled.get(k)
                    t_ch = time.perf_counter() if tracer.enabled else 0.0
                    T_dev = fn(T_dev) if fn is not None else advance(T_dev, k)
                    step += k
                    if tracer.enabled:
                        # dispatch-side span: the enqueue cost, not the
                        # device time (the loop deliberately never fences)
                        tracer.complete(f"chunk @{step}", drv_track, t_ch,
                                        cat="solve", args={"k": k})
                    if plan is not None:
                        plan.maybe_crash(step)
                        T_dev = plan.maybe_nan(step, T_dev)
                    if cfg.check_numerics:
                        if async_on:
                            if (pending_flag is not None
                                    and _settle_pending()):
                                continue  # rolled back: re-step the chunk
                            pending_flag = (
                                debug.finite_flag(T_dev), step,
                                async_io.device_snapshot(T_dev)
                                if rollback else None,
                                rollback and writer is not None
                                and cfg.checkpoint_every
                                and step % cfg.checkpoint_every == 0)
                        else:
                            try:
                                debug.check_finite(T_dev, step)
                            except FloatingPointError:
                                if _try_rollback(step):
                                    continue
                                raise
                            if rollback:
                                last_good = (async_io.device_snapshot(T_dev),
                                             step)
                    if cfg.heartbeat_every and step % cfg.heartbeat_every == 0:
                        master_print(" time_it:", step)  # fortran/serial/heat.f90:62
                    if cfg.checkpoint_every and step % cfg.checkpoint_every == 0:
                        if writer is not None:
                            if rollback and async_on:
                                pass  # deferred to _settle_pending: persist
                                      # only flag-verified snapshots
                            else:
                                _submit_snapshot(
                                    async_io.device_snapshot(T_dev), step)
                        else:
                            sync(T_dev)
                            T_ck = to_host(T_dev)
                            if T_ck is not None:
                                checkpoint.save(cfg, T_ck, step)
                            else:
                                checkpoint.save_shards(cfg, T_dev, step)
                if pending_flag is None or not _settle_pending():
                    break
                # final boundary flagged and rolled back: resume stepping
            t_sync = time.perf_counter() if tracer.enabled else 0.0
            sync(T_dev)
            if tracer.enabled:
                tracer.complete("final-sync", drv_track, t_sync,
                                cat="solve")
    except BaseException:
        # drain-on-exception: every queued snapshot still lands on disk (a
        # blow-up's last good boundary is exactly the state a resume
        # needs); a writer error is logged but never masks the solve error
        if writer is not None:
            writer.drain(raise_errors=False)
        raise
    solve_s = time.perf_counter() - t0
    if tracer.enabled:
        tracer.complete("solve", drv_track, t0, t0 + solve_s, cat="solve",
                        args={"steps": remaining, "n": cfg.n,
                              "backend": cfg.backend})
    if writer is not None:
        # post-solve flush, deliberately OUTSIDE solve_s: the device has
        # finished stepping, so the remaining writes overlap nothing —
        # they land in io_wait_s and the wall total. Backpressure waits
        # inside the loop above DO sit in solve_s (they stall stepping).
        writer.drain()

    tp_rate = tp_fell_back = None
    if two_point_repeats and remaining > 0:
        k0 = min(chunk, remaining)
        fn = compiled.get(k0) or (lambda t: advance(t, k0))
        # the copy (not T_dev) is donated into the protocol, so the solve's
        # final state survives the extra executions
        tp_res = two_point_rate(fn, jnp.copy(T_dev), cfg.points * k0,
                                repeats=two_point_repeats)
        tp_rate = tp_res[0]
        # surfaced so consumers that must not trust an overhead-dominated
        # rate (calibrate's stencil fits) can refuse it (review r5)
        tp_fell_back = tp_res.fell_back

    # fetch=False skips the final device->host copy (benchmark mode: the
    # copy is seconds for GiB-scale fields on a tunneled link and the caller
    # only wants timings)
    T_host = to_host(T_dev) if fetch else None
    gsum = gsum_dtype = None
    if cfg.report_sum:
        # The intended-but-commented-out global reduction of the reference
        # (mpi+cuda/heat.F90:266-273), done properly. With the field on host,
        # accumulate in f64 so every backend reports the identical sum
        # regardless of storage dtype; without (fetch=False), reduce on
        # device — a scalar fetch, so still cheap on a tunneled link — in
        # the widest dtype the platform allows, and LABEL the result
        # (gsum_dtype) so consumers never compare an f32-accumulated sum
        # against the f64 host path at 1e9-cell scale. A multi-host
        # deployment would psum process-local sums instead.
        if T_host is not None:
            gsum = float(np.sum(np.asarray(T_host, np.float64)))
            gsum_dtype = "float64"
        else:
            acc = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
            gsum = float(np.asarray(jnp.sum(T_dev, dtype=acc)))
            gsum_dtype = np.dtype(acc).name
    # precompile_s happened before t_all0 (in the caller's guard) — fold it
    # into the wall total as well
    timing = Timing(total_s=time.perf_counter() - t_all0 + precompile_s,
                    compile_s=compile_s,
                    solve_s=solve_s, steps=remaining, points=cfg.points,
                    points_per_s_two_point=tp_rate,
                    two_point_fell_back=tp_fell_back,
                    overlap_s=writer.hidden_s if writer is not None else None,
                    io_wait_s=writer.wait_s if writer is not None else None)
    return SolveResult(cfg=cfg, T=T_host, timing=timing, gsum=gsum,
                       gsum_dtype=gsum_dtype,
                       start_step=start_step, T_dev=T_dev)


def _rebuild_from_shard_blocks(cfg: HeatConfig, sharding, blocks):
    """Reassemble this process's checkpointed blocks into the global sharded
    array (multi-host resume: every process contributes its own blocks)."""
    from ..utils import jnp_dtype

    dt = jnp_dtype(cfg.dtype)
    idx_map = sharding.addressable_devices_indices_map(cfg.shape)
    by_start = {
        tuple(s.start or 0 for s in idx): dev for dev, idx in idx_map.items()
    }
    arrays = []
    for starts, data in blocks:
        dev = by_start.get(tuple(starts))
        if dev is None:
            raise ValueError(
                f"shard checkpoint block at offset {starts} does not match "
                f"the current mesh layout {sorted(by_start)} — resume with "
                f"the mesh shape the checkpoint was written under (or, if "
                f"the shape is unchanged, the shard->device ORDERING moved: "
                f"e.g. a JAX/topology change reordered build_mesh's device "
                f"placement between save and resume)")
        # host->target device in one hop (jnp.asarray would stage through
        # the default device first: a doubled transfer at GiB scale)
        arrays.append(jax.device_put(np.asarray(data).astype(dt), dev))
    return jax.make_array_from_single_device_arrays(cfg.shape, sharding, arrays)


_agree_round = 0  # KV keys must be fresh per agreement (SPMD-aligned calls)


def _allgather_steps(local: int) -> list:
    """Every process's newest shard step, exchanged through the distributed
    coordination service's KV store (gRPC) instead of an XLA collective:
    the CPU backend rejects multiprocess jit programs built outside the
    solve's own shard_map (found by the chaos-launch resume e2e —
    ``multihost_utils.process_allgather`` aborted every restarted world),
    and a 4-byte agreement has no business compiling a program anyway.
    ``blocking_key_value_get`` waits for each peer's key, so no barrier is
    needed; a peer that died pre-publish surfaces as the supervisor seeing
    its corpse, not as a deadlock (the get times out at 120 s)."""
    global _agree_round

    from jax._src.distributed import global_state

    client = getattr(global_state, "client", None)
    if client is None:
        # no coordination service (faked multi-host test seam): the
        # collective fallback — these tests never leave one real process
        from jax.experimental import multihost_utils

        return list(np.asarray(multihost_utils.process_allgather(
            jnp.asarray(local, jnp.int32))))
    _agree_round += 1
    base = f"heat_tpu/resume_step/r{_agree_round}"
    client.key_value_set(f"{base}/{jax.process_index()}", str(local))
    return [int(client.blocking_key_value_get(f"{base}/{i}", 120_000))
            for i in range(jax.process_count())]


def _agree_resume_step(local_step: Optional[int]) -> Optional[int]:
    """Cross-process agreement on the shard-checkpoint resume step.

    Processes can hold different latest steps (a crash between one
    process's save and the others'): resuming at different start_steps
    would desynchronize the collectives. Everyone resumes at the MINIMUM —
    the newest step that every process holds. If any process has no shard
    files at all the minimum is "none": all fall back together (never a
    silent IC start against peers mid-run)."""
    local = -1 if local_step is None else int(local_step)
    if jax.process_count() > 1:
        agreed = int(min(_allgather_steps(local)))
        if agreed != local:
            master_print(f"shard-checkpoint resume: local step {local} vs "
                         f"job-wide agreed step {agreed}")
    else:
        agreed = local
    return None if agreed < 0 else agreed


def resolve_initial_field(cfg: HeatConfig, T0: Optional[np.ndarray],
                          sharding=None):
    """(T_device, start_step) for device backends: explicit T0 > checkpoint
    (both host arrays, shipped over) > IC built directly on device."""
    from ..utils import jnp_dtype

    if (T0 is None and cfg.checkpoint_every and sharding is not None
            and hasattr(sharding, "addressable_devices_indices_map")):
        # multi-host runs checkpoint per-process shard files; prefer them
        # over a (possibly stale) single-host global snapshot
        sstep = _agree_resume_step(
            checkpoint.latest_shards(cfg, max_step=cfg.ntime))
        if sstep is not None:
            gstep = checkpoint.latest_step(cfg, max_step=cfg.ntime)
            if gstep is None or sstep >= gstep:
                blocks, step = checkpoint.load_shards(cfg, sstep)
                T = _rebuild_from_shard_blocks(cfg, sharding, blocks)
                master_print(f"resumed from shard checkpoints at step {step}")
                return T, step

    T0_host, start_step = load_or_init(cfg, T0, default_ic=False)
    if T0_host is None:
        from ..grid import initial_condition_device

        return initial_condition_device(cfg, sharding=sharding), start_step
    T = jnp.asarray(T0_host).astype(jnp_dtype(cfg.dtype))
    T = jax.device_put(T, sharding) if sharding is not None else jax.device_put(T)
    return T, start_step


def load_or_init(cfg: HeatConfig, T0: Optional[np.ndarray], default_ic: bool = True):
    """Resolve the starting field: explicit T0 > latest checkpoint > IC.

    With ``default_ic=False`` the IC fallback returns ``(None, 0)`` instead
    of a host array — device backends then build the IC directly on device
    (grid.initial_condition_device), avoiding the n^d host array and H2D
    transfer entirely.
    """
    from ..grid import initial_condition

    start_step = 0
    if T0 is None and cfg.checkpoint_every:
        ck = checkpoint.latest(cfg, max_step=cfg.ntime)
        if ck is not None:
            T0, start_step = checkpoint.load(ck, cfg)
            master_print(f"resumed from {ck} at step {start_step}")
    if T0 is None:
        if not default_ic:
            return None, 0
        T0 = initial_condition(cfg)
    return np.asarray(T0), start_step
