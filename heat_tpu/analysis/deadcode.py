"""Dead-code report (ISSUE 13 satellite): public package functions
unreachable from any entry point.

Generalizes the cross-module reachability idea of
``determinism._reachable`` from trace entries to the whole program: the
roots are every function defined OUTSIDE the package (tests, benchmark
drivers, repo-root scripts), every name referenced at package module
level, every decorated definition (decorators are registration), and
every dunder; the closure follows bare-name and attribute-leaf
references conservatively (any reference to the name reaches every
package function so named, and string constants count — ``getattr``/
registry tables resolve names from strings). What survives outside the
closure is a public function nothing can call — ``heat-tpu check
--dead-code`` lists it, informationally: the closure is conservative in
one direction only (it over-approximates liveness, so a listed function
really is unreachable; the interesting errors are omissions, not false
alarms).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Set

from .core import Context

_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _referenced_names(tree: ast.AST) -> Set[str]:
    """Every name a subtree can resolve a function through: bare names,
    attribute leaves (method/namespace calls), and identifier-shaped
    string constants (getattr, registry keys, CLI dispatch tables)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            names.add(node.id)
        elif isinstance(node, ast.Attribute):
            names.add(node.attr)
        elif (isinstance(node, ast.Constant)
              and isinstance(node.value, str)
              and _IDENT_RE.match(node.value)):
            names.add(node.value)
    return names


def _is_nested(fn: ast.FunctionDef) -> bool:
    cur = getattr(fn, "_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return True
        cur = getattr(cur, "_parent", None)
    return False


def _overrides_base(fn: ast.FunctionDef) -> bool:
    """Methods of classes WITH base classes may be framework hooks the
    base dispatches by name (BaseHTTPRequestHandler's do_GET/do_POST,
    log_message) — no static reference exists, so exempt them rather
    than cry wolf."""
    cur = getattr(fn, "_parent", None)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return bool(cur.bases or cur.keywords)
        cur = getattr(cur, "_parent", None)
    return False


def _module_level_names(tree: ast.Module) -> Set[str]:
    """Names referenced outside any function body (module execution,
    class-level statements, decorators, defaults) — everything that runs
    or binds at import time."""

    names: Set[str] = set()

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # the def itself binds at import time: its decorators,
                # defaults, and annotations evaluate — but not its body
                for dec in child.decorator_list:
                    names.update(_referenced_names(dec))
                for d in (child.args.defaults
                          + [x for x in child.args.kw_defaults if x]):
                    names.update(_referenced_names(d))
                continue
            if isinstance(child, ast.Name):
                names.add(child.id)
            elif isinstance(child, ast.Attribute):
                names.add(child.attr)
            elif (isinstance(child, ast.Constant)
                  and isinstance(child.value, str)
                  and _IDENT_RE.match(child.value)):
                names.add(child.value)
            visit(child)

    visit(tree)
    return names


def _external_sources(root: Path) -> List[Path]:
    """Entry-point files outside the package: the repo's tests/ and
    benchmarks/ trees plus top-level scripts, when the package sits in a
    repo checkout (site-packages installs simply contribute none)."""
    repo = root.parent
    out: List[Path] = []
    for sub in ("tests", "benchmarks"):
        d = repo / sub
        if d.is_dir():
            out.extend(sorted(d.rglob("*.py")))
    out.extend(sorted(p for p in repo.glob("*.py")))
    return [p for p in out if "__pycache__" not in p.parts]


def dead_code_report(root, extra_sources: Optional[List[Path]] = None
                     ) -> List[dict]:
    """Public, non-nested package functions outside the reachability
    closure, as ``{"path", "line", "qualname"}`` rows sorted by
    location. ``extra_sources`` overrides entry-point discovery (fixture
    trees in tests)."""
    root = Path(root)
    ctx = Context(root)

    # candidate table: name -> function records
    by_name: Dict[str, List[dict]] = {}
    funcs: List[dict] = []
    for src in ctx.sources:
        for fn in src.functions():
            if _is_nested(fn):
                continue
            rec = {"src": src, "fn": fn, "name": fn.name,
                   "qualname": getattr(fn, "_qualname", fn.name),
                   "seeded": (bool(fn.decorator_list)
                              or _overrides_base(fn))}
            funcs.append(rec)
            by_name.setdefault(fn.name, []).append(rec)

    # roots: names live by construction
    seeds: Set[str] = set()
    for src in ctx.sources:
        seeds |= _module_level_names(src.tree)
    ext = (_external_sources(root) if extra_sources is None
           else list(extra_sources))
    for p in ext:
        try:
            seeds |= _referenced_names(ast.parse(p.read_text()))
        except (OSError, SyntaxError, UnicodeDecodeError):
            continue

    reachable: Set[int] = set()
    work: List[dict] = []
    for rec in funcs:
        if (rec["seeded"] or rec["name"].startswith("__")
                or rec["name"] in seeds):
            reachable.add(id(rec["fn"]))
            work.append(rec)
    while work:
        rec = work.pop()
        for name in _referenced_names(rec["fn"]):
            for cand in by_name.get(name, ()):
                if id(cand["fn"]) not in reachable:
                    reachable.add(id(cand["fn"]))
                    work.append(cand)

    dead = [{"path": rec["src"].rel, "line": rec["fn"].lineno,
             "qualname": rec["qualname"]}
            for rec in funcs
            if id(rec["fn"]) not in reachable
            and not rec["name"].startswith("_")]
    dead.sort(key=lambda d: (d["path"], d["line"]))
    return dead
