"""Program auditor (ISSUE 13): jaxpr-level contracts for every compiled
family — the semantic tier of the invariant guard.

The AST suite (``heat-tpu check``) checks what the *source text*
promises; this module checks what the *compiler is actually handed*.
Every registered program family — the solo chunked advance
(``backends/common.solo_program_specs``), the packed-lane stepping,
tail, rollback, and loader programs plus the sharded mega-lane
(``serve/engine.lane_program_specs`` / ``mega_program_specs``) — is
traced on abstract inputs (``jax.make_jaxpr`` under ``enable_x64`` so a
silent f64 widening cannot hide behind x64-off canonicalization) and
AOT-lowered on whatever backend is present. Nothing executes; no chip
is needed. Five contract families, exposed as ``heat-tpu audit``:

``program-donation``
    Every buffer a family declares donated (the solo T/T_old double
    buffer, the serve chunk stacks, the mega-lane carried state) must
    appear in the lowered program's input/output alias table
    (``tf.aliasing_output``) — donation that quietly degrades to a copy
    is a silent 2x memory and bandwidth tax. Rollback-mode lane
    programs must provably NOT alias the field stack: the undonated
    input stack IS the boundary snapshot (the PR-9 no-copy contract,
    previously guarded only by a runtime spy test).
``program-purity``
    Zero ``pure_callback`` / ``io_callback`` / ``debug_callback`` /
    host-callback primitives anywhere in a hot program's jaxpr — the
    trace-level complement of the AST ``hot-path-purity`` rule, which
    cannot see through closures or library calls.
``program-dtype``
    No silent f64 promotion in any non-f64 family (traced under x64,
    where an unpinned python/numpy scalar widens visibly), f64 families
    must actually carry f64, and bfloat16 families must show the
    storage-round ``convert_element_type`` pairs INSIDE the step loop —
    the byte-identity mechanism, until now a convention.
``compile-budget``
    The full stepping-program key space implied by a ``ServeConfig``
    (bucket x tier x chunk/tail x kernel x donation, plus one loader
    per bucket x tier) is enumerated through the engine's own
    ``chunk_cache_key`` seam and gated against the budget declared in
    the committed registry; the key *dimensions* are read off the
    seam's signature — a refactor that adds a recompile dimension fails
    here instead of as a production compile storm (PR 4's at-most-one-
    compile-per-combo guarantee, made mechanical). Mega-lane programs
    are keyed per request geometry and are deliberately outside this
    bound (admission, not compilation, limits them).
``program-digest``
    A canonicalized jaxpr digest per family, committed to
    ``analysis/digests/programs.json`` exactly like the record-schema
    registry: drift fails the audit with the op-level delta named, and
    ``--update-digests`` is the reviewed-change workflow. The registry
    also exports each program's static FLOP/byte estimate (XLA cost
    analysis plus the Williams-roofline bytes-per-lane-step model) —
    ``heat-tpu perfcheck`` cross-checks the learned cost model against
    that prior (0.1-10x band, informational off-TPU).

GSPMD's lesson (PAPERS.md) is that the compiled program is the ground
truth worth inspecting; the digests make inspecting it a diff review
instead of an archaeology project.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import inspect
import json
import re
from pathlib import Path
from typing import Callable, Dict, List, Optional, Set, Tuple

from .core import Violation

# Host-callback primitives that must never appear in a hot program: each
# one fences the dispatch pipeline on every execution. debug.print
# lowers to debug_callback; outside_call/host_callback_call are the
# legacy host-callback spellings.
BANNED_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "outside_call",
    "host_callback_call"})

# Primitives whose sub-jaxpr executes per step / per grid cell: a
# storage-round convert found under one of these runs every mini-step,
# which is what the bf16 byte-identity contract requires.
_LOOP_PRIMS = frozenset({"while", "scan", "pallas_call"})

_HEX_RE = re.compile(r"0x[0-9a-fA-F]+")
_MAIN_SIG_RE = re.compile(r"@main\((.*?)\)\s*->", re.S)
_BUCKET_RE = re.compile(r"(\d+)d/n(\d+)/([a-z0-9]+)/")
_DTYPE_BYTES = {"float64": 8, "float32": 4, "bfloat16": 2}

# contract id -> one-line doc (the audit's analogue of core.RULE_DOCS)
CONTRACTS: Dict[str, str] = {
    "program-donation": "declared-donated buffers appear in the lowered "
                        "alias table; rollback programs alias nothing",
    "program-purity": "no host-callback primitives in any hot program's "
                      "jaxpr",
    "program-dtype": "no silent f64 promotion (x64 trace); bf16 "
                     "families storage-round inside the step loop",
    "compile-budget": "stepping-program key space enumerated via "
                      "chunk_cache_key and gated against the declared "
                      "budget",
    "program-digest": "canonical jaxpr digest per family gated against "
                      "digests/programs.json (op-level delta on drift)",
}

# `make check` runs these; the dtype contract rides the same trace but
# its verdicts are the slowest-moving, so the full set is the lab tier
# (benchmarks/extras_r5c.sh) per the ISSUE's fast/full split.
FAST_CONTRACTS: Tuple[str, ...] = (
    "program-digest", "program-donation", "program-purity",
    "compile-budget")


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One registered program family, abstractly buildable.

    ``build()`` returns ``(fn, args, static_argnums)``: a jitted
    callable, the argument tuple to trace/lower it with (array slots as
    ``jax.ShapeDtypeStruct``, static slots as python scalars), and the
    static argument positions for ``jax.make_jaxpr``. ``donated`` holds
    FLAT lowered-argument indices (every registered family takes a flat
    list of array arguments, so python position == MLIR %arg index).
    """

    name: str
    build: Callable[[], tuple]
    donated: Tuple[int, ...] = ()
    no_alias: bool = False       # rollback contract: alias table empty
    hot: bool = True             # on the serve/solve dispatch path
    dtype: str = "float32"
    storage_round: bool = False  # bf16: convert pairs inside the loop
    steps: int = 0               # static chunk size traced with
    lanes: int = 1
    kernel: str = "xla"
    family: str = "lane"         # solo | lane | loader | mega
    bucket: Optional[str] = None  # cost-model bucket label, when lane


# spec.name -> trace dict; tracing every family costs seconds, and the
# audit, its tests, and cmd_info may all want the same traces in one
# process. Seeded-violation fixtures use fresh names (or cache=False).
_TRACE_CACHE: Dict[str, dict] = {}


def iter_program_specs() -> List[ProgramSpec]:
    """Every registered program family, collected through the registry
    seams. Building specs is cheap (no tracing happens until
    ``trace_program``)."""
    from ..backends.common import solo_program_specs
    from ..serve.engine import lane_program_specs, mega_program_specs

    return (solo_program_specs() + lane_program_specs()
            + mega_program_specs())


def _sub_jaxprs(val) -> list:
    """Jaxprs hiding in one eqn-param value (closed or open, possibly
    nested in lists/tuples) — duck-typed so no private jax imports."""
    if hasattr(val, "eqns"):
        return [val]
    if hasattr(val, "jaxpr"):
        return _sub_jaxprs(val.jaxpr)
    if isinstance(val, (list, tuple)):
        return [j for v in val for j in _sub_jaxprs(v)]
    return []


def _walk_eqns(jaxpr, in_loop: bool = False):
    """Yield ``(eqn, in_loop)`` over a jaxpr and every nested sub-jaxpr;
    ``in_loop`` is True once the ancestor chain crosses a primitive
    whose body executes per step (while/scan/pallas grid)."""
    for eqn in jaxpr.eqns:
        yield eqn, in_loop
        child_loop = in_loop or eqn.primitive.name in _LOOP_PRIMS
        for val in eqn.params.values():
            for sub in _sub_jaxprs(val):
                yield from _walk_eqns(sub, child_loop)


def trace_program(spec: ProgramSpec, cache: bool = True) -> dict:
    """Trace + lower one family on abstract inputs; no execution.

    The jaxpr is taken under ``enable_x64`` (uniformly, so f64 families
    keep their dtype and a silent widening in any family becomes
    visible); the lowering runs in the production dtype mode (donation
    and cost are mode-independent, and it is the program a real run
    compiles). Returns primitive histogram, aval dtypes, storage-round
    converts, canonical digest, alias text, and static cost."""
    if cache and spec.name in _TRACE_CACHE:
        return _TRACE_CACHE[spec.name]
    import jax

    fn, args, static_argnums = spec.build()
    with jax.experimental.enable_x64():
        closed = jax.make_jaxpr(fn, static_argnums=tuple(static_argnums))(
            *args)
    prims: collections.Counter = collections.Counter()
    avals: Set[str] = set()
    converts: List[Tuple[bool, str]] = []
    for var in list(closed.jaxpr.invars) + list(closed.jaxpr.outvars):
        a = getattr(var, "aval", None)
        if a is not None and hasattr(a, "dtype"):
            avals.add(str(a.dtype))
    for eqn, in_loop in _walk_eqns(closed.jaxpr):
        prims[eqn.primitive.name] += 1
        for var in list(eqn.invars) + list(eqn.outvars):
            a = getattr(var, "aval", None)
            if a is not None and hasattr(a, "dtype"):
                avals.add(str(a.dtype))
        if eqn.primitive.name == "convert_element_type":
            converts.append((in_loop, str(eqn.params.get("new_dtype"))))
    canon = _HEX_RE.sub("0xX", str(closed))
    digest = hashlib.sha256(canon.encode()).hexdigest()[:16]
    lowered_text = cost = lower_error = None
    try:
        lowered = fn.lower(*args)
        lowered_text = lowered.as_text()
        try:
            ca = lowered.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if ca:
                cost = {"flops": int(ca.get("flops", 0) or 0),
                        "bytes": int(ca.get("bytes accessed", 0) or 0)}
        except Exception:     # cost analysis is best-effort per backend
            cost = None
    except Exception as e:    # lowering failure is a finding, not a crash
        lower_error = f"{type(e).__name__}: {e}"
    tr = {"digest": digest, "prims": prims, "avals": avals,
          "converts": converts, "lowered_text": lowered_text,
          "cost": cost, "lower_error": lower_error}
    if cache:
        _TRACE_CACHE[spec.name] = tr
    return tr


def donated_arg_indices(lowered_text: str) -> Set[int]:
    """Flat %arg indices carrying ``tf.aliasing_output`` in the lowered
    module's public @main signature — the input/output alias table the
    compiler is handed. Segment the signature on %argN tokens (argument
    types never contain %arg, so the split is safe even with loc()/
    sharding attributes in between)."""
    m = _MAIN_SIG_RE.search(lowered_text)
    if m is None:
        return set()
    parts = re.split(r"%arg(\d+)", m.group(1))
    return {int(parts[i]) for i in range(1, len(parts) - 1, 2)
            if "tf.aliasing_output" in parts[i + 1]}


# --- the five contract checkers ---------------------------------------------
# Each takes (spec, trace) [budget takes explicit inputs] and returns
# plain Violations with the family name as the path, so seeded-violation
# fixtures exercise them without touching the real registry.

def check_donation(spec: ProgramSpec, tr: dict) -> List[Violation]:
    loc = f"<{spec.name}>"
    if tr["lowered_text"] is None:
        if spec.donated or spec.no_alias:
            return [Violation(
                "program-donation", loc, 0,
                f"family could not be lowered, so its alias table is "
                f"unverifiable ({tr['lower_error']})")]
        return []
    aliased = donated_arg_indices(tr["lowered_text"])
    out: List[Violation] = []
    for i in spec.donated:
        if i not in aliased:
            out.append(Violation(
                "program-donation", loc, 0,
                f"arg {i} is declared donated but the lowered program's "
                f"alias table does not alias it to any output (aliased "
                f"args: {sorted(aliased) or 'none'}) — the double-buffer "
                f"ping-pong silently became a copy"))
    if spec.no_alias and aliased:
        out.append(Violation(
            "program-donation", loc, 0,
            f"rollback-mode program must NOT alias its inputs (the "
            f"undonated input stack IS the boundary snapshot — PR-9 "
            f"no-copy contract) but args {sorted(aliased)} alias "
            f"outputs: a restore would read a consumed buffer"))
    return out


def check_purity(spec: ProgramSpec, tr: dict) -> List[Violation]:
    if not spec.hot:
        return []
    return [Violation(
        "program-purity", f"<{spec.name}>", 0,
        f"hot program contains `{prim}` x{tr['prims'][prim]} — a host "
        f"callback inside a chunk program fences the dispatch pipeline "
        f"on every execution (jaxpr-level complement of the AST "
        f"hot-path-purity rule)")
        for prim in sorted(BANNED_CALLBACK_PRIMS & set(tr["prims"]))]


def check_dtype(spec: ProgramSpec, tr: dict) -> List[Violation]:
    loc = f"<{spec.name}>"
    out: List[Violation] = []
    if spec.dtype != "float64" and "float64" in tr["avals"]:
        out.append(Violation(
            "program-dtype", loc, 0,
            f"silent f64 promotion: a {spec.dtype} family traced under "
            f"enable_x64 carries float64 intermediates (avals: "
            f"{sorted(tr['avals'])}) — an unpinned python/numpy scalar "
            f"widened the computation"))
    if spec.dtype == "float64" and "float64" not in tr["avals"]:
        out.append(Violation(
            "program-dtype", loc, 0,
            f"float64 family shows no float64 avals (saw "
            f"{sorted(tr['avals'])}) — the storage dtype was lost in "
            f"tracing"))
    if spec.storage_round:
        in_loop = {nd for il, nd in tr["converts"] if il}
        if not ({"bfloat16", "float32"} <= in_loop):
            out.append(Violation(
                "program-dtype", loc, 0,
                f"bfloat16 family must round through storage on every "
                f"mini-step: expected convert_element_type pairs "
                f"(->float32 upcast, ->bfloat16 round) INSIDE the step "
                f"loop, saw {sorted(in_loop) or 'none'} — byte-identity "
                f"with the solo path rests on this mechanism"))
    return out


def enumerate_step_keys(scfg=None) -> Dict[str, int]:
    """The full distinct-program key space a ServeConfig implies, walked
    through the engine's own ``chunk_cache_key`` seam: every (bucket
    geometry x dtype x bc) x lane-tier x {chunk, tail} x available
    kernel under the config's donation mode, plus one loader program
    per (bucket, tier). This is the worst case a serving process can
    compile — the scheduler only ever builds a subset."""
    from ..ops.pallas_stencil import lane_kernel_available
    from ..serve.engine import (_BC_LO, BucketKey, chunk_cache_key,
                                lane_tier, tail_size)

    if scfg is None:
        from ..serve.scheduler import ServeConfig

        scfg = ServeConfig()
    donate = scfg.on_nan != "rollback"
    tiers = sorted({lane_tier(i, scfg.lanes)
                    for i in range(1, scfg.lanes + 1)})
    ks = [scfg.chunk]
    tail = tail_size(scfg.chunk)
    if tail:
        ks.append(tail)
    step_keys: set = set()
    loaders: set = set()
    for ndim in (2, 3):
        for side in scfg.buckets:
            for dtype in sorted(_DTYPE_BYTES):
                for bc in sorted(_BC_LO):
                    bk = BucketKey(ndim, side, dtype, bc)
                    kernels = ["xla"]
                    if (scfg.lane_kernel != "xla" and dtype != "float64"
                            and lane_kernel_available(ndim, side, dtype)):
                        kernels.append("pallas")
                    for tier in tiers:
                        loaders.add((bk, tier, donate))
                        for k in ks:
                            for kern in kernels:
                                step_keys.add(chunk_cache_key(
                                    bk, tier, k, kern, donate))
    return {"step_keys": len(step_keys), "loaders": len(loaders),
            "total": len(step_keys) + len(loaders)}


def check_compile_budget(registry: Optional[dict],
                         key_dims: Optional[Tuple[str, ...]] = None,
                         enumerated: Optional[int] = None
                         ) -> List[Violation]:
    """Gate the stepping-program key space against the declared budget.
    ``key_dims``/``enumerated`` default to the live seam (signature of
    ``chunk_cache_key`` / ``enumerate_step_keys()``); tests pass fakes
    to seed violations without monkeypatching the engine."""
    from ..serve.engine import STEP_KEY_DIMS, chunk_cache_key

    loc = "analysis/digests/programs.json"
    live_dims = key_dims is None
    if key_dims is None:
        key_dims = tuple(inspect.signature(chunk_cache_key).parameters)
    if enumerated is None:
        enumerated = enumerate_step_keys()["total"]
    out: List[Violation] = []
    if live_dims and key_dims != STEP_KEY_DIMS:
        out.append(Violation(
            "compile-budget", "serve/engine.py", 0,
            f"chunk_cache_key signature {list(key_dims)} disagrees with "
            f"its own STEP_KEY_DIMS declaration {list(STEP_KEY_DIMS)} — "
            f"update both together"))
    decl = (registry or {}).get("compile_budget")
    if not decl:
        out.append(Violation(
            "compile-budget", loc, 0,
            "no declared compile budget in the digest registry — run "
            "`heat-tpu audit --update-digests` and commit it"))
        return out
    if list(key_dims) != list(decl.get("key_dims", [])):
        out.append(Violation(
            "compile-budget", loc, 0,
            f"stepping-program key dimensions changed: declared "
            f"{decl.get('key_dims')}, live {list(key_dims)} — a new "
            f"recompile dimension multiplies the program count; if "
            f"intentional, `heat-tpu audit --update-digests` and commit "
            f"the reviewed budget"))
    max_programs = decl.get("max_programs", 0)
    if enumerated > max_programs:
        out.append(Violation(
            "compile-budget", loc, 0,
            f"enumerated stepping-program key space ({enumerated}) "
            f"exceeds the declared budget ({max_programs}) — a compile "
            f"storm in waiting; if the growth is intentional, "
            f"`heat-tpu audit --update-digests` re-declares the budget"))
    return out


def _op_delta(old_ops: Dict[str, int], new_ops: Dict[str, int]) -> str:
    added = sorted(set(new_ops) - set(old_ops))
    removed = sorted(set(old_ops) - set(new_ops))
    changed = sorted(k for k in set(old_ops) & set(new_ops)
                     if old_ops[k] != new_ops[k])
    parts = []
    if added:
        parts.append("added " + ", ".join(f"{k} x{new_ops[k]}"
                                          for k in added))
    if removed:
        parts.append("removed " + ", ".join(f"{k} x{old_ops[k]}"
                                            for k in removed))
    if changed:
        parts.append("count " + ", ".join(
            f"{k} {old_ops[k]}->{new_ops[k]}" for k in changed))
    return "; ".join(parts) or ("identical op histogram — operand "
                                "structure or constants changed")


def check_digests(table: Dict[str, dict], registry: Optional[dict]
                  ) -> List[Violation]:
    loc = "analysis/digests/programs.json"
    if registry is None:
        return [Violation(
            "program-digest", loc, 0,
            "digest registry missing/unreadable — generate it with "
            "`heat-tpu audit --update-digests` and commit it")]
    old = registry.get("programs", {})
    out: List[Violation] = []
    for name in sorted(set(old) | set(table)):
        if name not in table:
            out.append(Violation(
                "program-digest", loc, 0,
                f"program family {name!r} is in the committed registry "
                f"but no longer registered — if intentional, `heat-tpu "
                f"audit --update-digests` and commit the diff"))
        elif name not in old:
            out.append(Violation(
                "program-digest", loc, 0,
                f"new program family {name!r} (digest "
                f"{table[name]['digest']}) not in the committed registry "
                f"— run `heat-tpu audit --update-digests` so the new "
                f"program lands reviewed"))
        elif old[name].get("digest") != table[name]["digest"]:
            out.append(Violation(
                "program-digest", loc, 0,
                f"program digest drifted for {name!r}: "
                f"{old[name].get('digest')} -> {table[name]['digest']}; "
                f"op-level delta: "
                f"{_op_delta(old[name].get('ops', {}), table[name]['ops'])}"
                f" — the compiled program changed; if intentional, "
                f"`heat-tpu audit --update-digests` and review the diff "
                f"(TROUBLESHOOTING.md: program digest drifted)"))
    return out


# --- static cost model (the roofline prior) ---------------------------------

def roofline_lane_step_bytes(ndim: int, n: int, dtype: str) -> int:
    """One masked stencil step over one lane's padded bucket buffer
    moves the full state twice — one read, one write of (B+2)^ndim
    cells (Williams et al. roofline: the stencil is bandwidth-bound at
    ~0.4 flops/byte, so bytes are the cost)."""
    return 2 * (n + 2) ** ndim * _DTYPE_BYTES[dtype]


def lane_static_prior(bucket: str, kernel: str = "xla"
                      ) -> Optional[float]:
    """Static seconds-per-lane-step prior for a cost-model bucket label
    (``2d/n256/float32/edges``): roofline bytes over the machine model's
    sustained HBM bandwidth. Kernel choice does not move the bandwidth
    bound, so it only disambiguates the label. None when the label does
    not parse — callers treat that as 'no prior'."""
    m = _BUCKET_RE.match(bucket)
    if m is None or m.group(3) not in _DTYPE_BYTES:
        return None
    from .. import machine

    bw = machine.current().hbm_bytes_per_s
    if not bw:
        return None
    return roofline_lane_step_bytes(
        int(m.group(1)), int(m.group(2)), m.group(3)) / bw


# --- registry ----------------------------------------------------------------

def default_registry_path() -> Path:
    return Path(__file__).resolve().parent / "digests" / "programs.json"


def load_registry(path) -> Optional[dict]:
    try:
        return json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return None


def digest_table(specs: List[ProgramSpec], traces: Dict[str, dict]
                 ) -> Dict[str, dict]:
    """The per-family registry payload: canonical digest, op histogram
    (so drift reports can name the delta), and the static cost export
    perfcheck cross-checks the learned model against."""
    table: Dict[str, dict] = {}
    for spec in specs:
        tr = traces.get(spec.name)
        if tr is None:
            continue
        ent = {"digest": tr["digest"],
               "ops": {k: int(v) for k, v in sorted(tr["prims"].items())},
               "dtype": spec.dtype, "kernel": spec.kernel,
               "family": spec.family, "steps": spec.steps}
        if tr.get("cost"):
            ent["flops"] = tr["cost"]["flops"]
            ent["bytes_accessed"] = tr["cost"]["bytes"]
        if spec.bucket:
            m = _BUCKET_RE.match(spec.bucket)
            ent["bucket"] = spec.bucket
            if m:
                ent["roofline_bytes_per_lane_step"] = (
                    roofline_lane_step_bytes(int(m.group(1)),
                                             int(m.group(2)), m.group(3)))
        table[spec.name] = ent
    return table


def write_registry(path, table: Dict[str, dict],
                   enumerated: Dict[str, int],
                   key_dims: Optional[Tuple[str, ...]] = None) -> None:
    import jax

    from ..serve.engine import chunk_cache_key

    if key_dims is None:
        key_dims = tuple(inspect.signature(chunk_cache_key).parameters)
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = {
        "version": 1,
        "jax": jax.__version__,
        "comment": "committed program-digest registry — regenerate with "
                   "`heat-tpu audit --update-digests` and review the "
                   "diff (TROUBLESHOOTING.md: program digest drifted). "
                   "Digests canonicalize the traced jaxpr; flops/bytes "
                   "are this platform's static cost analysis and are "
                   "informational.",
        "compile_budget": {"key_dims": list(key_dims),
                           "max_programs": enumerated["total"],
                           "enumerated": dict(sorted(enumerated.items()))},
        "programs": table,
    }
    path.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")


# --- the audit entry point ---------------------------------------------------

def audit(registry_path=None, update_digests: bool = False,
          contracts=None, specs: Optional[List[ProgramSpec]] = None
          ) -> Tuple[List[Violation], dict]:
    """Run the program audit: trace every registered family, apply the
    selected contract families (default: all of ``CONTRACTS``), gate
    digests/budget against the committed registry (or rewrite it with
    ``update_digests``). Returns ``(violations, report)`` — exit-code
    semantics and printing live in the CLI."""
    import jax

    reg_path = Path(registry_path) if registry_path else (
        default_registry_path())
    selected = tuple(contracts) if contracts else tuple(CONTRACTS)
    unknown = [c for c in selected if c not in CONTRACTS]
    if unknown:
        raise ValueError(f"unknown contract families {unknown}; "
                         f"known: {sorted(CONTRACTS)}")
    specs = list(specs) if specs is not None else iter_program_specs()
    out: List[Violation] = []
    traces: Dict[str, dict] = {}
    for spec in specs:
        try:
            traces[spec.name] = trace_program(spec)
        except Exception as e:   # an untraceable family is a finding
            out.append(Violation(
                "program-trace", f"<{spec.name}>", 0,
                f"family failed to trace: {type(e).__name__}: {e}"))
    for spec in specs:
        tr = traces.get(spec.name)
        if tr is None:
            continue
        if "program-donation" in selected:
            out.extend(check_donation(spec, tr))
        if "program-purity" in selected:
            out.extend(check_purity(spec, tr))
        if "program-dtype" in selected:
            out.extend(check_dtype(spec, tr))
    table = digest_table(specs, traces)
    enum = enumerate_step_keys() if (
        "compile-budget" in selected or update_digests) else None
    if update_digests:
        write_registry(reg_path, table, enum)
    registry = load_registry(reg_path)
    if "compile-budget" in selected:
        out.extend(check_compile_budget(registry,
                                        enumerated=enum["total"]))
    digest_gate = "updated" if update_digests else "skipped"
    if "program-digest" in selected and not update_digests:
        skew = (registry is not None
                and registry.get("jax") != jax.__version__)
        if skew:
            # a jax upgrade legitimately reshapes jaxprs; the gate
            # resumes once the registry is regenerated under the new
            # version — drift within one version stays a hard failure
            digest_gate = (f"skipped — registry written under jax "
                           f"{registry.get('jax')}, running "
                           f"{jax.__version__}; regenerate with "
                           f"--update-digests")
        else:
            digest_gate = "checked"
            out.extend(check_digests(table, registry))
    out.sort(key=lambda v: (v.path, v.line, v.rule, v.message))
    report = {
        "families": len(specs),
        "traced": len(traces),
        "contracts": list(selected),
        "jax": jax.__version__,
        "registry": str(reg_path),
        "registry_programs": len((registry or {}).get("programs", {})),
        "budget": {
            "declared": ((registry or {}).get("compile_budget") or {}
                         ).get("max_programs"),
            "enumerated": enum,
        },
        "digest_gate": digest_gate,
        "programs": table,
        "violations": len(out),
    }
    return out, report
