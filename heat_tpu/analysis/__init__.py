"""Invariant guard: the project-native static-analysis suite.

Ten PRs in, the serving stack's hardest-won properties were enforced
only by convention — the zero-sync hot loop, the engine→observatory
lock order, the Mosaic kernel hardening lessons, the never-flickering
record schema. Dapper's lesson (PAPERS.md) is that cross-cutting
guarantees survive only when checked mechanically at every site; this
package is that check, exposed as ``heat-tpu check`` / ``make check``.

Six rule families (one module each, registered into
``core.RULE_FAMILIES``):

====================  =====================================================
``hot-path-purity``   no device syncs / eager fetches in the serve
                      dispatch paths outside the allow-marked seams
``lock-discipline``   gateway < engine < observatory lock order, no
                      I/O/device work under the engine lock (static
                      half; ``HEAT_TPU_LOCKCHECK=1`` arms the dynamic
                      watchdog in ``runtime/debug.py``)
``traced-determinism``  no clocks/entropy/env/set-iteration reachable
                      from jit / pallas_call / shard_map entries
``mosaic-kernel-safety``  the PR-9 Mosaic lessons as lints over
                      ``ops/pallas_stencil.py`` kernel bodies
``record-schema``     every ``json_record`` site statically resolved and
                      gated against ``analysis/schemas/records.json``
``races``             Eraser-style lockset inference over the thread-
                      shared serving objects: per-field write-guard
                      intersection gated against
                      ``analysis/schemas/guards.json``; a field written
                      from two threads with no common lock fails (static
                      half; ``HEAT_TPU_RACECHECK=1`` arms the dynamic
                      sanitizer in ``runtime/debug.py``)
====================  =====================================================

Sanctioned exceptions carry ``# heat-tpu: allow[rule-id] reason`` markers
next to the code (reason mandatory). Markers that no longer suppress
anything are reported as stale (``heat-tpu check`` warns;
``--strict-allows`` fails), and ``heat-tpu check --dead-code`` lists
public functions outside the reachability closure (``deadcode``). The
suite is pure ``ast`` — it lints a tree it never imports, so it runs in
seconds with no device, no JAX session, and inside CI's smallest box.

A second, separate tier — the **program auditor** (``programs``, exposed
as ``heat-tpu audit``) — checks contracts that no AST lint can see:
it traces every registered program family to jaxprs and AOT-lowered
StableHLO on abstract inputs (no execution, no chip) and machine-checks
donation, traced purity, dtype discipline, the compile-key budget, and
drift-gated program digests (``analysis/digests/programs.json``). It
needs JAX importable but nothing else, so it is NOT imported here: the
AST tier must keep running in a tree where JAX is broken.
"""

from . import (deadcode, determinism, locks, mosaic, purity,  # noqa: F401
               races, schema)
from .core import (RULE_DOCS, RULE_FAMILIES, Context, Violation,  # noqa: F401
                   run_checks)
