"""Rule family 4 — **Mosaic kernel safety** (``mosaic-kernel-safety``).

PR 9 hardened the lane kernels against the *real* Mosaic compiler the
hard way: interpret-mode tier-1 passed while chipless v5e AOT compiles
rejected the kernels one missing lowering at a time. Each lesson became a
code pattern; this rule codifies them as lints scoped to the kernel
bodies of ``ops/pallas_stencil.py`` so the next kernel author hits a
``heat-tpu check`` failure in seconds instead of a Mosaic stack trace in
the compile-check lab (or worse, at serve time on a chip):

- ``isfinite``: no ``jnp.isfinite`` / ``lax.is_finite`` in a kernel body
  — Mosaic has no ``is_finite`` lowering; spell it ``|x| < inf`` (false
  for NaN and both infinities — compares with NaN are false).
- ``narrow-select``: no ``jnp.where`` whose operand was just downcast to
  a sub-32-bit dtype — Mosaic rejects sub-32-bit selects; keep the band
  in the 32-bit accumulation dtype holding storage-rounded values
  (``.astype(store).astype(acc)``) and select in 32 bits.
- ``multiply-mask``: no mask-multiplied updates (``mask * upd`` where the
  mask derives from a comparison or a 0/1 ``where``) in lane kernels —
  ``0 * NaN`` is NaN, so a poisoned lane leaks through the very mask
  meant to confine it; use a select (``jnp.where(keep, upd, band)``).
  The *solo* kernels' multiplicative freeze is allow-marked: their bands
  are NaN-free by construction (no foreign lanes) and the form is the
  reference's interior guard.
- ``shrinking-roll``: no rotates of *shrunken* slices — a roll whose
  operand traces back to a bounded-slice subscript hands Mosaic a
  sublane-misaligned rotate shape, rejected outright by current
  compilers; run constant-shape full-band rotates every mini-step (the
  lane kernels' proven shape discipline). The solo 3D kernel's aligned
  shrinking slices predate the rule and are allow-marked with the lab
  that proves them.

Kernel bodies are found structurally: functions passed (directly or via
a ``_make_*`` factory call) as the first argument of ``pl.pallas_call``,
every ``def`` nested inside those factories, plus same-file helpers the
bodies call (``_lane_finite_accumulate``, ``_assemble_band``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Context, Violation, attr_chain, register

_ACC_NAMES = {"acc_dt", "acc", "accum", "float32", "f32", "int32"}
_NARROW_NAMES = {"store_dt", "store", "bfloat16", "float16", "bf16", "f16"}


def _kernel_bodies(src) -> List[ast.FunctionDef]:
    byname: Dict[str, ast.FunctionDef] = {f.name: f for f in src.functions()}
    roots: List[ast.FunctionDef] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if not chain or chain[-1] != "pallas_call":
            continue
        arg = node.args[0] if node.args else None
        ref: Optional[str] = None
        if isinstance(arg, ast.Name):
            ref = arg.id
        elif isinstance(arg, ast.Call):
            achain = attr_chain(arg.func)
            ref = achain[-1] if achain else None
        if ref and ref in byname:
            roots.append(byname[ref])
    bodies: List[ast.FunctionDef] = []
    seen: Set[str] = set()
    work = list(roots)
    while work:
        fn = work.pop()
        q = getattr(fn, "_qualname", fn.name)
        if q in seen:
            continue
        seen.add(q)
        bodies.append(fn)
        # nested defs (the factory's inner `kernel`) and same-file helper
        # calls from the body
        for inner in ast.walk(fn):
            if isinstance(inner, ast.FunctionDef) and inner is not fn:
                work.append(inner)
            if isinstance(inner, ast.Call):
                chain = attr_chain(inner.func)
                if chain and chain[-1] in byname:
                    work.append(byname[chain[-1]])
    return bodies


def _bindings(fn: ast.FunctionDef) -> Dict[str, List[ast.AST]]:
    """name -> every expression assigned to it in this function (simple
    single-target assignments only) — the one-hop dataflow the detectors
    resolve Names through."""
    out: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            out.setdefault(node.targets[0].id, []).append(node.value)
    return out


def _resolve(expr: ast.AST, env: Dict[str, List[ast.AST]],
             depth: int = 0, seen: Optional[Set[str]] = None
             ) -> List[ast.AST]:
    """The expression plus everything its Names bind to (bounded)."""
    if seen is None:
        seen = set()
    out = [expr]
    if depth >= 4:
        return out
    for name_node in ast.walk(expr):
        if isinstance(name_node, ast.Name) and name_node.id not in seen:
            seen.add(name_node.id)
            for bound in env.get(name_node.id, []):
                out.extend(_resolve(bound, env, depth + 1, seen))
    return out


def _mask_sources(expr: ast.AST, env, depth: int = 0):
    """Yield the *top-level* expressions a mult operand ultimately names,
    unwrapping ``.astype(...)`` chains and subscripts and following Name
    bindings a few hops — deliberately shallow (no subtree walking): a
    select result that merely *contains* a comparison deep inside is not
    a mask, but a value whose top node IS a comparison (or a 0-branch
    where) is."""
    if depth > 3:
        return
    e = expr
    while True:
        if (isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute)
                and e.func.attr == "astype"):
            e = e.func.value
            continue
        if isinstance(e, ast.Subscript):
            e = e.value
            continue
        break
    if isinstance(e, ast.Name):
        for bound in env.get(e.id, []):
            yield from _mask_sources(bound, env, depth + 1)
        return
    yield e


def _num_const(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float))
    return (isinstance(node, ast.UnaryOp)
            and isinstance(node.operand, ast.Constant))


def _is_masky(expr: ast.AST, env) -> bool:
    """Is this mult operand a *mask* (comparison-derived, or a where with
    a constant branch — the ``where(frozen, 0.0, r)`` freeze form) that
    multiplication would leak ``0 * NaN`` through?"""
    for e in _mask_sources(expr, env):
        if isinstance(e, ast.Compare):
            return True
        if isinstance(e, ast.Call):
            chain = attr_chain(e.func)
            if (chain and chain[-1] == "where"
                    and any(_num_const(a) for a in e.args[1:3])):
                return True
    return False


def _astype_target_narrow(call: ast.Call) -> bool:
    """``x.astype(<narrow>)`` where <narrow> names a sub-32-bit dtype."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "astype" and call.args):
        return False
    t = call.args[0]
    names = {n.id for n in ast.walk(t) if isinstance(n, ast.Name)}
    names |= {n.attr for n in ast.walk(t) if isinstance(n, ast.Attribute)}
    if isinstance(t, ast.Constant) and isinstance(t.value, str):
        names.add(t.value)
    return bool(names & _NARROW_NAMES) and not (names & _ACC_NAMES)


def _has_shrunk_slice(expr: ast.AST, env) -> bool:
    """Does the rolled operand resolve (through Name bindings) to a
    value whose TOP-LEVEL form is a bounded-slice subscript — the
    shrinking-band shape? Only top-level resolved expressions are
    inspected: a helper call that merely *takes* a sliced argument
    (``_assemble_band(refs[:9], ...)`` — a tuple-of-refs slice) is not a
    shrunken array."""
    for e in _resolve(expr, env):
        while (isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute)
               and e.func.attr == "astype"):
            e = e.func.value
        if isinstance(e, ast.Subscript):
            sl = e.slice
            elts = (sl.elts if isinstance(sl, ast.Tuple) else [sl])
            for part in elts:
                if isinstance(part, ast.Slice) and (
                        part.lower is not None
                        or part.upper is not None):
                    return True
    return False


@register("mosaic-kernel-safety",
          "PR-9 Mosaic lessons as lints over pallas_stencil kernel "
          "bodies: no isfinite, no sub-32-bit select, no multiply-"
          "masking, no shrinking-slice rotates")
def check(ctx: Context) -> List[Violation]:
    out: List[Violation] = []
    reported: Set[Tuple[str, int, str]] = set()

    def emit(src, lineno, kind, msg):
        key = (src.rel, lineno, kind)
        if key in reported:
            return
        reported.add(key)
        out.append(Violation("mosaic-kernel-safety", src.rel, lineno, msg))

    for src in ctx.sources:
        if not src.rel.endswith("ops/pallas_stencil.py"):
            continue
        for fn in _kernel_bodies(src):
            env = _bindings(fn)
            q = getattr(fn, "_qualname", fn.name)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    chain = attr_chain(node.func)
                    leaf = chain[-1] if chain else ""
                    if leaf in ("isfinite", "is_finite"):
                        emit(src, node.lineno, "isfinite",
                             f"isfinite: `{'.'.join(chain)}` in kernel "
                             f"body {q} — Mosaic has no is_finite "
                             f"lowering; spell it `|x| < inf` (false for "
                             f"NaN and both infinities)")
                    elif leaf == "where" and chain[0] in ("jnp", "lax",
                                                          "jax"):
                        for arg in node.args[1:3]:
                            narrow = any(
                                isinstance(e, ast.Call)
                                and _astype_target_narrow(e)
                                for e in _resolve(arg, env))
                            if narrow:
                                emit(src, node.lineno, "narrow-select",
                                     f"narrow-select: jnp.where over a "
                                     f"sub-32-bit operand in kernel body "
                                     f"{q} — Mosaic rejects sub-32-bit "
                                     f"selects; round through storage "
                                     f"but select in the 32-bit "
                                     f"accumulation dtype "
                                     f"(.astype(store).astype(acc))")
                                break
                    elif leaf == "roll":
                        if node.args and _has_shrunk_slice(node.args[0],
                                                           env):
                            emit(src, node.lineno, "shrinking-roll",
                                 f"shrinking-roll: rotate of a shrunken "
                                 f"slice in kernel body {q} — sublane-"
                                 f"misaligned rotate shapes are rejected "
                                 f"by Mosaic; use constant-shape "
                                 f"full-band rotates every mini-step")
                if isinstance(node, ast.BinOp) and isinstance(node.op,
                                                              ast.Mult):
                    for side in (node.left, node.right):
                        if _is_masky(side, env):
                            emit(src, node.lineno, "multiply-mask",
                                 f"multiply-mask: mask-multiplied update "
                                 f"in kernel body {q} — 0*NaN is NaN, so "
                                 f"a poisoned value leaks through the "
                                 f"mask; select instead "
                                 f"(jnp.where(keep, upd, band))")
                            break
    return out
