"""Rule family 1 — **hot-path purity** (``hot-path-purity``).

The serve hot loop's whole performance story (the dispatch-ahead rework,
PR 4; the roofline argument in PAPERS.md) rests on one discipline: the
dispatch and round-robin paths enqueue device work and NEVER wait on it —
the only device→host transfer is the boundary fetch, funneled through the
``host_fetch`` / ``fetch_boundary`` seams so it can be watchdogged,
traced, and monkeypatch-proven. One stray ``.item()`` or eager
``jnp.asarray`` in ``dispatch_fill`` silently re-fences every chunk and
the A/B labs degrade to the sync fallback without anyone changing a flag.

This rule bans, inside the **hot function set** (the dispatch/round-robin
paths of ``serve/scheduler.py`` and the chunk-program builders of
``serve/engine.py``):

- ``.item()`` / ``.block_until_ready()`` / ``jax.device_get`` — explicit
  device syncs;
- ``np.asarray`` / ``np.array`` / ``jnp.asarray`` / ``jnp.array`` /
  direct ``host_fetch`` — eager host round trips of device buffers;
- any eager ``jnp.*`` call in the *scheduler-side* hot functions (every
  ``jnp`` dispatch there is a python→device round trip; traced builder
  bodies are exempt — their ``jnp`` is staged, not eager);
- ``float(...)`` / ``int(...)`` applied to a boundary ``handle`` (the
  classic scalarization sync).

The sanctioned seams — ``host_fetch``, ``fetch_boundary``,
``LaneEngine.fetch_remaining`` — are *in* the hot set and carry explicit
``# heat-tpu: allow[hot-path-purity]`` markers: the rule proves every
other site clean and the markers document why those three are the
exception (ISSUE 11's allowlist contract).
"""

from __future__ import annotations

import ast
from typing import List

from .core import (Context, Violation, attr_chain, call_name, dotted,
                   register)

# qualnames (suffix-matched against FunctionDef._qualname) per file.
# Scheduler side: eager jnp is banned too. Builder side: only the
# sync/round-trip calls (their bodies are traced — jnp there is staged).
SCHEDULER_HOT = (
    "_GroupRunner.dispatch_fill", "_GroupRunner.process_boundary",
    "_GroupRunner.sync_round", "_GroupRunner._judge_lanes",
    "_GroupRunner._maybe_poison",
    "MegaLaneRunner.dispatch_fill", "MegaLaneRunner.process_boundary",
    "MegaLaneRunner.sync_round", "MegaLaneRunner._judge",
    "MegaLaneRunner._maybe_poison",
    "Engine.run", "Engine._serve_loop",
)
ENGINE_HOT = (
    "LaneEngine.dispatch_chunk", "MegaLaneEngine.dispatch_chunk",
    "make_lane_advance", "make_lane_loader", "_lane_step",
    # the sanctioned seams themselves — their D2H calls carry markers
    "host_fetch", "fetch_boundary", "LaneEngine.fetch_remaining",
)

_SYNC_CALLS = {"item", "block_until_ready", "device_get"}
_FETCH_CALLS = {"asarray", "array", "host_fetch"}
_ARRAY_MODULES = {"np", "numpy", "jnp"}


def _hot_functions(src, quals):
    for fn in src.functions():
        q = getattr(fn, "_qualname", fn.name)
        for want in quals:
            if q == want or q.endswith("." + want):
                yield fn, want
                break


def _check_fn(src, fn: ast.FunctionDef, ban_eager_jnp: bool,
              out: List[Violation]) -> None:
    seen_lines = set()

    def report(node, msg):
        key = (node.lineno, msg)
        if key in seen_lines:
            return
        seen_lines.add(key)
        out.append(Violation("hot-path-purity", src.rel, node.lineno, msg))

    q = getattr(fn, "_qualname", fn.name)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        chain = attr_chain(node.func)
        if name in _SYNC_CALLS:
            report(node, f"device sync `{dotted(node.func) or name}()` in "
                         f"hot function {q} — the dispatch path must "
                         f"never fence (route through the boundary-fetch "
                         f"seam)")
        elif name in _FETCH_CALLS and (
                name == "host_fetch"
                or (chain and chain[0] in _ARRAY_MODULES)):
            report(node, f"eager host round trip "
                         f"`{dotted(node.func) or name}(...)` in hot "
                         f"function {q} — the only sanctioned D2H is the "
                         f"host_fetch/fetch_boundary seam")
        elif (ban_eager_jnp and chain and chain[0] == "jnp"
              and len(chain) >= 2):
            report(node, f"eager `{'.'.join(chain)}` dispatch in "
                         f"scheduler hot function {q} — every jnp call "
                         f"here is a python->device round trip per "
                         f"boundary (use numpy on the host mirror, or "
                         f"move it into the compiled chunk program)")
        elif (isinstance(node.func, ast.Name)
              and node.func.id in ("float", "int") and node.args):
            arg_names = {n.id for n in ast.walk(node.args[0])
                         if isinstance(n, ast.Name)}
            if arg_names & {"handle", "boundary_handle"}:
                report(node, f"`{node.func.id}()` scalarization of a "
                             f"device boundary handle in hot function "
                             f"{q} — fetch through the seam instead")


@register("hot-path-purity",
          "no device syncs / eager fetches in the serve dispatch paths")
def check(ctx: Context) -> List[Violation]:
    out: List[Violation] = []
    for src in ctx.sources:
        if src.rel.endswith("serve/scheduler.py"):
            for fn, _ in _hot_functions(src, SCHEDULER_HOT):
                _check_fn(src, fn, ban_eager_jnp=True, out=out)
        elif src.rel.endswith("serve/engine.py"):
            for fn, _ in _hot_functions(src, ENGINE_HOT):
                _check_fn(src, fn, ban_eager_jnp=False, out=out)
    return out
