"""Shared infrastructure for the invariant-guard checker suite.

Everything here is plain ``ast`` walking over the package source — the
checkers never import the modules they analyze (an analyzer that needs a
working JAX install to lint a file cannot run in a broken tree, which is
exactly when you want it). The pieces:

- :class:`Violation` — one finding, formatted ``path:line: [rule] msg``.
- :class:`Source` — a parsed file: AST (with parent/qualname annotations),
  raw lines, and the **allowlist markers** extracted from comments.
- :class:`Context` — every Source under the scanned root plus per-run
  options; rules receive it whole (the lock and schema rules are
  cross-file by nature).
- :func:`run_checks` — load, dispatch to the registered rule families,
  filter allow-marked findings, return the survivors.

Allowlist marker grammar (the sanctioned-seam escape hatch)::

    some_call()   # heat-tpu: allow[rule-id] why this site is sanctioned

The marker covers the physical lines of the statement it sits on (or the
statement directly below, when written on its own line). The reason text
is MANDATORY — a bare marker is itself a violation: the whole point is
that every exception to an invariant carries its justification next to
the code, reviewable in the same diff.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

_MARKER_RE = re.compile(
    r"#\s*heat-tpu:\s*allow\[(?P<rule>[a-z0-9-]+)\]\s*(?P<reason>.*)$")


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding. ``rule`` is the family id (``RULE_FAMILIES`` key);
    ``kind`` a finer sub-rule slug carried in the message for families
    with several detectors (mosaic-kernel-safety)."""

    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Source:
    """One parsed Python file with qualname-annotated AST and markers."""

    def __init__(self, root: Path, path: Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(path))
        self._annotate()
        # lineno -> {rule_id: reason}; rule "*" would defeat the point and
        # is deliberately not supported. Scanned over COMMENT tokens, not
        # raw lines: marker grammar quoted inside a string literal (help
        # text, docs) must not become a live — and instantly stale —
        # marker.
        self.allows: Dict[int, Dict[str, str]] = {}
        self.bare_markers: List[int] = []
        for tok in tokenize.generate_tokens(io.StringIO(self.text).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _MARKER_RE.search(tok.string)
            if not m:
                continue
            i = tok.start[0]
            if not m.group("reason").strip():
                self.bare_markers.append(i)
                continue
            self.allows.setdefault(i, {})[m.group("rule")] = (
                m.group("reason").strip())

    def _annotate(self) -> None:
        """Attach ``_qualname`` to every FunctionDef and ``_parent`` to
        every node (the purity/mosaic scopes are qualname lists; parents
        let detectors look outward from a match)."""

        def visit(node, parents: Tuple[str, ...]):
            for child in ast.iter_child_nodes(node):
                child._parent = node  # type: ignore[attr-defined]
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    q = parents + (child.name,)
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        child._qualname = ".".join(q)  # type: ignore
                    visit(child, q)
                else:
                    visit(child, parents)

        self.tree._parent = None  # type: ignore[attr-defined]
        visit(self.tree, ())

    def functions(self) -> List[ast.FunctionDef]:
        return [n for n in ast.walk(self.tree)
                if isinstance(n, ast.FunctionDef)]


class Context:
    """All sources under one root + run options, handed to every rule."""

    def __init__(self, root: Path, schema_registry: Optional[Path] = None,
                 update_schemas: bool = False):
        self.root = Path(root)
        self.schema_registry = (Path(schema_registry) if schema_registry
                                else self.root / "analysis" / "schemas"
                                / "records.json")
        self.update_schemas = update_schemas
        self.sources: List[Source] = []
        self.errors: List[Violation] = []
        for p in sorted(self.root.rglob("*.py")):
            if "__pycache__" in p.parts:
                continue
            try:
                self.sources.append(Source(self.root, p))
            except (SyntaxError, UnicodeDecodeError) as e:
                self.errors.append(Violation(
                    "parse", p.relative_to(self.root).as_posix(),
                    getattr(e, "lineno", 0) or 0,
                    f"cannot parse: {type(e).__name__}: {e}"))

    def source(self, rel_suffix: str) -> Optional[Source]:
        """The unique source whose relative path ends with ``rel_suffix``
        (e.g. ``serve/scheduler.py``), or None."""
        hits = [s for s in self.sources if s.rel.endswith(rel_suffix)]
        return hits[0] if len(hits) == 1 else None


# --- small AST helpers shared by the rule modules ---------------------------

def call_name(node: ast.Call) -> str:
    """The called name: ``f`` for ``f(...)``, ``attr`` for ``x.y.attr(...)``."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def attr_chain(node: ast.AST) -> List[str]:
    """``["self", "prof", "note_terminal"]`` for ``self.prof.note_terminal``;
    empty when the expression is not a plain name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


def dotted(node: ast.AST) -> str:
    return ".".join(attr_chain(node))


def enclosing_function(node: ast.AST) -> Optional[ast.FunctionDef]:
    cur = getattr(node, "_parent", None)
    while cur is not None:
        if isinstance(cur, ast.FunctionDef):
            return cur
        cur = getattr(cur, "_parent", None)
    return None


# --- registry ----------------------------------------------------------------

# family id -> check(ctx) -> List[Violation]; populated by register() calls
# at the bottom of each rule module (importing heat_tpu.analysis loads all).
RULE_FAMILIES: Dict[str, Callable[[Context], List[Violation]]] = {}
RULE_DOCS: Dict[str, str] = {}


def register(rule_id: str, doc: str):
    def deco(fn):
        RULE_FAMILIES[rule_id] = fn
        RULE_DOCS[rule_id] = doc
        return fn
    return deco


def run_checks(root, rules: Optional[List[str]] = None,
               schema_registry=None, update_schemas: bool = False,
               strict_allows: bool = False
               ) -> Tuple[List[Violation], dict]:
    """Run the requested rule families (default: all) over ``root``.

    Returns ``(violations, stats)``. Allow-marked findings are dropped
    here (every rule reports raw and this one chokepoint applies the
    markers, so marker semantics cannot drift per rule); a marker with
    no reason text is converted into its own violation.

    Because filtering happens at this chokepoint, we also know which
    markers actually suppressed something. The rest are **stale**: the
    rule id is unknown (typo, or the rule was removed), or the rule ran
    and no longer fires at that site (the code was fixed but the marker
    stayed, silently pre-authorizing a future regression). Stale markers
    are reported in ``stats["stale_allows"]``; with ``strict_allows``
    they become ``stale-allow`` violations. Markers for known rules that
    were not selected this run are left alone — we cannot tell.
    """
    from . import (determinism, locks, mosaic, purity, races,  # noqa: F401
                   schema)
    # (imports register the families; flake-quiet because the side effect
    # IS the point)

    ctx = Context(root, schema_registry=schema_registry,
                  update_schemas=update_schemas)
    selected = list(RULE_FAMILIES) if not rules else list(rules)
    unknown = [r for r in selected if r not in RULE_FAMILIES]
    if unknown:
        raise ValueError(f"unknown rule families {unknown}; "
                         f"known: {sorted(RULE_FAMILIES)}")
    out: List[Violation] = list(ctx.errors)
    for src in ctx.sources:
        for ln in src.bare_markers:
            out.append(Violation(
                "allow-marker", src.rel, ln,
                "allow marker without a reason — every sanctioned "
                "exception must carry its justification"))
    per_rule: Dict[str, int] = {}
    consumed: set = set()  # (rel, marker_line, rule) that suppressed a hit
    for rid in selected:
        found = RULE_FAMILIES[rid](ctx)
        kept = []
        for v in found:
            src = next((s for s in ctx.sources if s.rel == v.path), None)
            marker = (None if src is None
                      else _allow_line(src, v.rule, v.line))
            if marker is not None:
                consumed.add((v.path, marker, v.rule))
                continue
            kept.append(v)
        per_rule[rid] = len(kept)
        out.extend(kept)
    stale: List[dict] = []
    for src in ctx.sources:
        for ln, rules_here in sorted(src.allows.items()):
            for rule, reason in sorted(rules_here.items()):
                if rule not in RULE_FAMILIES:
                    why = (f"unknown rule id {rule!r} — typo, or the "
                           "rule was removed")
                elif rule not in selected:
                    continue  # rule didn't run: can't judge the marker
                elif (src.rel, ln, rule) not in consumed:
                    why = ("rule no longer fires here — the marker "
                           "silently pre-authorizes a regression")
                else:
                    continue
                stale.append({"path": src.rel, "line": ln, "rule": rule,
                              "reason": reason, "why": why})
    if strict_allows:
        out.extend(Violation("stale-allow", s["path"], s["line"],
                             f"stale allow[{s['rule']}] marker: {s['why']}"
                             f" (reason given: {s['reason']!r})")
                   for s in stale)
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    stats = {"files": len(ctx.sources), "rules": selected,
             "violations": len(out), "per_rule": per_rule,
             "allow_markers": sum(len(d) for s in ctx.sources
                                  for d in s.allows.values()),
             "stale_allows": stale}
    return out, stats


def _allow_line(src: Source, rule: str, line: int) -> Optional[int]:
    """The line of the allow marker covering ``line`` for ``rule``, or
    None. The marker may sit on the flagged line, within the two lines
    above (the tail of a comment block annotating a short statement
    pair), or — for a call spanning lines — on a trailing continuation
    line."""
    for ln in range(line - 2, line + 3):
        if rule in src.allows.get(ln, {}):
            return ln
    return None
