"""Rule family 3 — **traced-code determinism** (``traced-determinism``).

Everything the engine promises — bit-identical replay, golden-trace
regression locks, the dispatch-ahead countdown mirror's exactness — rests
on traced code being a pure function of its inputs. A ``time.time()``
read, a ``random`` draw, or iteration over an unordered ``set`` inside a
function that gets traced by ``jit`` / ``pallas_call`` / ``shard_map``
bakes one arbitrary value (or one arbitrary *program order*) into the
compiled executable: results then differ between compiles, the
persistent-cache key stops meaning anything, and the byte-identity gates
fail unreproducibly — the worst kind of flake.

Mechanics: the rule finds trace **entry points** (functions decorated
with ``jit``/``partial(jax.jit, ...)``, passed to ``pallas_call`` /
``shard_map`` / ``jax.jit(...)``/``jax.vmap(...)``), builds a
conservative same-repo call graph (name references inside the entry and
its enclosing factory, with function-scoped ``from ..x import y`` imports
resolved across scanned modules), and bans inside every reachable
function:

- wall-clock reads: ``time.*``, ``perf_counter``/``monotonic``,
  ``datetime.*``, ``wall_clock`` (the engine's own clock seam);
- entropy: ``random.*``, ``np.random.*``, ``secrets.*``, ``uuid.*``;
- environment reads: ``os.environ`` / ``os.getenv`` (a traced branch on
  an env var is a compile-time fork nobody versioned);
- iteration over an unordered ``set`` (``for x in set(...)``, set
  literals/comprehensions) — ``sorted(...)`` around it is the fix and
  passes automatically.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Context, Violation, attr_chain, register

_CLOCK_BASES = {"time", "datetime"}
_ENTROPY_BASES = {"random", "secrets", "uuid"}
_BANNED_NAMES = {"perf_counter", "monotonic", "wall_clock", "time_ns",
                 "getenv"}


def _is_jit_decorator(dec: ast.AST) -> bool:
    chain = attr_chain(dec)
    if chain and chain[-1] in ("jit", "pallas_call", "shard_map"):
        return True
    if isinstance(dec, ast.Call):
        # functools.partial(jax.jit, ...) and jax.jit(...) forms
        fchain = attr_chain(dec.func)
        if fchain and fchain[-1] in ("jit", "pallas_call", "shard_map"):
            return True
        if fchain and fchain[-1] == "partial" and dec.args:
            achain = attr_chain(dec.args[0])
            if achain and achain[-1] in ("jit", "pallas_call",
                                         "shard_map"):
                return True
    return False


def _entry_functions(src) -> List[ast.FunctionDef]:
    entries = []
    byname: Dict[str, ast.FunctionDef] = {f.name: f
                                          for f in src.functions()}
    for fn in src.functions():
        if any(_is_jit_decorator(d) for d in fn.decorator_list):
            entries.append(fn)
    # functions passed by name into jit/pallas_call/shard_map/vmap calls
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if not chain or chain[-1] not in ("jit", "pallas_call",
                                          "shard_map", "vmap"):
            continue
        for arg in node.args[:1]:
            ref = None
            if isinstance(arg, ast.Name):
                ref = arg.id
            elif isinstance(arg, ast.Call):
                # pallas_call(_make_kernel(...)) — the factory's inner
                # defs are the kernel bodies
                achain = attr_chain(arg.func)
                ref = achain[-1] if achain else None
            if ref and ref in byname:
                f = byname[ref]
                entries.append(f)
                entries.extend(n for n in ast.walk(f)
                               if isinstance(n, ast.FunctionDef))
    return entries


def _function_scope_imports(fn: ast.FunctionDef) -> Dict[str, str]:
    """name -> source module tail, for ``from ..x.y import name`` inside
    the function (the deferred-import idiom this repo uses)."""
    out = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                out[alias.asname or alias.name] = (
                    node.module.rsplit(".", 1)[-1] + ":" + alias.name)
    return out


def _reachable(ctx: Context, src, entry: ast.FunctionDef
               ) -> List[Tuple[object, ast.FunctionDef]]:
    """(source, function) pairs conservatively reachable from ``entry``:
    same-module functions referenced by name from the entry or its
    enclosing factory chain, plus cross-module functions named in
    function-scoped imports, one hop deep per module."""
    by_src: Dict[str, Dict[str, ast.FunctionDef]] = {}
    mod_of: Dict[str, List] = {}
    for s in ctx.sources:
        by_src[s.rel] = {f.name: f for f in s.functions()}
        mod_of.setdefault(s.path.stem, []).append(s)

    seen: Set[Tuple[str, str]] = set()
    work: List[Tuple[object, ast.FunctionDef]] = [(src, entry)]
    # the enclosing factory's locals (step_all = vmap(partial(f, ...)))
    # bind helpers the entry calls through; include the factory itself
    parent = getattr(entry, "_parent", None)
    while parent is not None:
        if isinstance(parent, ast.FunctionDef):
            work.append((src, parent))
        parent = getattr(parent, "_parent", None)
    out = []
    while work:
        s, fn = work.pop()
        key = (s.rel, getattr(fn, "_qualname", fn.name))
        if key in seen:
            continue
        seen.add(key)
        out.append((s, fn))
        imports = _function_scope_imports(fn)
        names = {n.id for n in ast.walk(fn) if isinstance(n, ast.Name)}
        local = by_src[s.rel]
        for name in names:
            if name in local and local[name] is not fn:
                work.append((s, local[name]))
            elif name in imports:
                mod_tail, fname = imports[name].split(":")
                for cand in mod_of.get(mod_tail, []):
                    f2 = by_src[cand.rel].get(fname)
                    if f2 is not None:
                        work.append((cand, f2))
    return out


def _check_body(src, fn: ast.FunctionDef, entry_q: str,
                out: List[Violation], seen: Set) -> None:
    q = getattr(fn, "_qualname", fn.name)
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            base = chain[0] if chain else ""
            leaf = chain[-1] if chain else ""
            bad = None
            if base in _CLOCK_BASES or leaf in _BANNED_NAMES & {
                    "perf_counter", "monotonic", "wall_clock", "time_ns"}:
                bad = "wall-clock read"
            elif base in _ENTROPY_BASES or (
                    len(chain) >= 2 and chain[:2] == ["np", "random"]):
                bad = "entropy source"
            elif leaf == "getenv" or (len(chain) >= 2
                                      and chain[-2:] == ["os", "environ"]):
                bad = "environment read"
            if bad:
                key = (src.rel, node.lineno, bad)
                if key not in seen:
                    seen.add(key)
                    out.append(Violation(
                        "traced-determinism", src.rel, node.lineno,
                        f"{bad} `{'.'.join(chain)}` in {q}, reachable "
                        f"from traced entry {entry_q} — traced code must "
                        f"be a pure function of its inputs (hoist the "
                        f"value to an argument)"))
        if isinstance(node, ast.Attribute) and node.attr == "environ":
            chain = attr_chain(node)
            if chain[:1] == ["os"]:
                key = (src.rel, node.lineno, "environ")
                if key not in seen:
                    seen.add(key)
                    out.append(Violation(
                        "traced-determinism", src.rel, node.lineno,
                        f"environment read `os.environ` in {q}, "
                        f"reachable from traced entry {entry_q} — a "
                        f"traced env branch is an unversioned "
                        f"compile-time fork"))
        it = None
        if isinstance(node, (ast.For,)):
            it = node.iter
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                               ast.DictComp)):
            it = node.generators[0].iter
        if it is not None and _is_unordered(it):
            key = (src.rel, it.lineno, "set-iter")
            if key not in seen:
                seen.add(key)
                out.append(Violation(
                    "traced-determinism", src.rel, it.lineno,
                    f"iteration over an unordered set in {q}, reachable "
                    f"from traced entry {entry_q} — program order bakes "
                    f"into the compiled executable; wrap in sorted()"))


def _is_unordered(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        chain = attr_chain(node.func)
        return bool(chain) and chain[-1] in ("set", "frozenset")
    return False


@register("traced-determinism",
          "no clocks/entropy/env reads/set iteration reachable from "
          "jit/pallas_call/shard_map entry points")
def check(ctx: Context) -> List[Violation]:
    out: List[Violation] = []
    seen: Set = set()
    for src in ctx.sources:
        for entry in _entry_functions(src):
            entry_q = getattr(entry, "_qualname", entry.name)
            for s, fn in _reachable(ctx, src, entry):
                _check_body(s, fn, f"{src.rel}:{entry_q}", out, seen)
    return out
