"""Rule family 6 — **cross-thread guard-map analysis** (``races``).

PR 11's ``lock-discipline`` rule proves locks are acquired in rank
order; nothing proved shared state is actually *protected* — an
attribute written with no lock at all passed every check. This rule is
the static half of the classic lockset pair (Eraser, Savage et al.
SOSP '97; ThreadSanitizer, Serebryany & Iskhodzhanov WBIA '09;
PAPERS.md): infer, per shared field, the set of locks that guard every
write, and fail the build when a field written from two threads has an
empty guard intersection. The dynamic half (``HEAT_TPU_RACECHECK=1``,
``runtime/debug.py``) checks the same property at runtime from the
lock-order watchdog's per-thread held stacks.

Mechanics, in four passes over the package AST:

1. **Thread roster.** Thread-shared *classes* are seeded from spawn
   sites (``threading.Thread(target=self._m, name="...")`` — the method
   is an entry on that named thread), from ``BaseHTTPRequestHandler``
   subclasses (every ``do_*`` method is an ``http-handler`` entry), and
   from lock ownership (a class that builds a ``make_lock``/
   ``threading.Lock`` field declared itself shared). Classes whose
   constructor takes a monitored class as an annotated parameter
   (``outer: "Engine"`` — the runner pattern) join the set too. Public
   methods of externally-constructed classes are entries on the
   ``client`` thread; ``DRIVER_ENTRIES`` pins the offline drive path
   (``Engine.run``) to the same logical thread as the online scheduler
   loop — the API contract makes the two drive modes mutually
   exclusive, and without the pin every runner field would read as
   cross-thread when the modes can never coexist.
2. **Thread propagation.** Entry labels flow along a conservative
   call-graph closure — ``self.m()``, calls through constructor-typed
   fields and locals (``self.prof = Observatory(...)``;
   ``writer = SnapshotWriter(...)``), nested functions (a local
   function passed to ``writer.submit`` runs on the writer thread —
   ``SINK_CALLS``), with ``determinism._reachable`` reused for
   module-level spawn targets. Internal classes (every constructor
   site inside monitored methods) inherit their constructors' threads.
3. **Access classification.** Every ``self.f`` (and typed
   ``self.outer.f``) access in a monitored class is recorded as
   read/write with its guard set: lexically enclosing ``with <lock>:``
   items, plus locks every caller provably holds at every call site of
   a ``_``-private helper (the helper-held fixpoint). ``Condition``
   fields alias to the lock they wrap; ``Event``/``Queue``/
   ``Semaphore`` fields are self-synchronizing and their method calls
   are not accesses. ``__init__`` writes are construction
   (happens-before publication) and exempt.
4. **The guard map.** Per field, the write-guard intersection decides
   the committed classification in ``analysis/schemas/guards.json``:
   ``lock:<name>`` (a common guard), ``thread-confined(<t>)`` /
   ``single-writer(<t>)`` (one writing thread), ``unguarded-readonly``
   (no post-init writes), or ``allow(<reason>)`` for violating fields
   sanctioned with ``# heat-tpu: allow[races] why``. A field written
   from >= 2 threads with an empty intersection and no marker is a
   violation; the map itself is drift-gated exactly like the record
   registry — ``heat-tpu check --update-schemas`` rewrites it and the
   diff rides the same PR as the code change.
"""

from __future__ import annotations

import ast
import json
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import (Context, Source, Violation, _allow_line, attr_chain,
                   register)
from .determinism import _reachable

# Offline drive entry points that share the online scheduler thread's
# logical identity (see module docstring, pass 1).
DRIVER_THREAD = "driver"
DRIVER_ENTRIES: Dict[Tuple[str, str], str] = {
    ("Engine", "run"): DRIVER_THREAD,
}

# (receiver name, call attr) -> thread: a function object passed as an
# argument runs on that thread (the SnapshotWriter job-submission seam).
SINK_CALLS: Dict[Tuple[str, str], str] = {
    ("writer", "submit"): "heat-snapshot-writer",
}

CLIENT = "client"
INIT = "init"

_LOCK_FACTORIES = {"make_lock", "Lock", "RLock"}
_SELFSYNC_FACTORIES = {"Event", "Queue", "SimpleQueue", "Semaphore",
                       "BoundedSemaphore", "Barrier"}
_MUTATORS = {"append", "appendleft", "add", "extend", "insert", "remove",
             "discard", "pop", "popleft", "popitem", "clear", "update",
             "setdefault", "sort", "reverse", "subtract"}
_ANNOT_NAME_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


class _ClassInfo:
    """Everything the rule knows about one monitored class."""

    def __init__(self, src: Source, node: ast.ClassDef):
        self.src = src
        self.node = node
        self.name = node.name
        self.methods: Dict[str, ast.FunctionDef] = {
            n.name: n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.lock_fields: Dict[str, str] = {}   # field -> canonical lock
        self.selfsync: Set[str] = set()
        self.typed: Dict[str, str] = {}         # ref field -> class name
        self.entries: Dict[str, str] = {}       # method -> thread label
        self.ctor_threads: Set[str] = set()     # threads that construct it
        self.external = False                   # constructed outside the
        #                                         monitored closure
        self.is_handler = any(
            attr_chain(b)[-1:] == ["BaseHTTPRequestHandler"]
            for b in node.bases)


def _enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    cur = getattr(node, "_parent", None)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = getattr(cur, "_parent", None)
    return None


def _enclosing_unit(node: ast.AST) -> Optional[ast.FunctionDef]:
    cur = getattr(node, "_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "_parent", None)
    return None


def _shallow(fn: ast.AST):
    """Nodes of ``fn`` excluding nested function bodies (a nested def is
    its own unit — it may run on a different thread than its encloser)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _annot_class(node: Optional[ast.AST], classes: Dict[str, _ClassInfo]
                 ) -> Optional[str]:
    """The monitored class named by a parameter annotation — handles
    ``Engine``, ``"Engine"`` and ``Optional["Engine"]`` shapes."""
    if node is None:
        return None
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on 3.9+
        return None
    for name in _ANNOT_NAME_RE.findall(text):
        if name in classes:
            return name
    return None


def _thread_of_spawn(call: ast.Call) -> Tuple[Optional[List[str]], str]:
    """(target attr chain, thread label) for a ``threading.Thread(...)``
    call; (None, "") when it is not one or the target is opaque."""
    chain = attr_chain(call.func)
    if not chain or chain[-1] != "Thread":
        return None, ""
    target = None
    label = ""
    for kw in call.keywords:
        if kw.arg == "target":
            target = attr_chain(kw.value)
        elif kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            label = kw.value.value
    if not target:
        return None, ""
    return target, (label or target[-1])


class _Model:
    """The package-wide model: monitored classes, thread sets per
    (class, unit), and the raw access stream."""

    def __init__(self, ctx: Context):
        self.ctx = ctx
        self.classes: Dict[str, _ClassInfo] = {}
        # (class, unit-name) -> set of thread labels
        self.threads: Dict[Tuple[str, str], Set[str]] = {}
        # (class, unit-name) -> locks provably held on every entry
        self.entry_held: Dict[Tuple[str, str], Optional[frozenset]] = {}
        # accesses: (class, field, kind, unit-key, guards, src, line)
        self.accesses: List[tuple] = []
        self._index_classes()
        self._seed_entries()
        self._propagate_threads()
        self._collect_accesses()

    # -- pass 1: class index, lock fields, typing, constructor sites ----
    def _all_classes(self):
        for src in self.ctx.sources:
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef):
                    yield src, node

    def _index_classes(self) -> None:
        by_name: Dict[str, List] = {}
        for src, node in self._all_classes():
            by_name.setdefault(node.name, []).append((src, node))
        # unambiguous names only: two classes sharing a name cannot be
        # told apart at a constructor site, so neither is typed/monitored
        candidates = {n: v[0] for n, v in by_name.items() if len(v) == 1}

        def info_of(name):
            src, node = candidates[name]
            ci = _ClassInfo(src, node)
            self._scan_fields(ci)
            return ci

        infos = {n: info_of(n) for n in candidates}
        monitored: Set[str] = set()
        for n, ci in infos.items():
            if ci.lock_fields or ci.is_handler or self._spawns(ci):
                monitored.add(n)
        # second wave: runner-pattern classes (ctor annotated with a
        # monitored class) join the set
        for n, ci in infos.items():
            if n in monitored:
                continue
            init = ci.methods.get("__init__")
            if init is None:
                continue
            for a in init.args.args[1:]:
                if _annot_class(a.annotation, {m: infos[m]
                                               for m in monitored}):
                    monitored.add(n)
                    break
        self.classes = {n: infos[n] for n in monitored}
        # typed ref fields may point at any monitored class
        for ci in self.classes.values():
            self._scan_typed(ci)

    def _spawns(self, ci: _ClassInfo) -> bool:
        for node in ast.walk(ci.node):
            if isinstance(node, ast.Call):
                target, _ = _thread_of_spawn(node)
                if target and target[:1] == ["self"] and len(target) == 2:
                    return True
        return False

    def _scan_fields(self, ci: _ClassInfo) -> None:
        """Lock / condition / self-synchronizing fields from ``self.f =
        <factory>(...)`` assignments anywhere in the class."""
        cond_wraps: Dict[str, Optional[str]] = {}
        for node in ast.walk(ci.node):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1):
                continue
            t = node.targets[0]
            tc = attr_chain(t)
            if len(tc) != 2 or tc[0] != "self":
                continue
            if not isinstance(node.value, ast.Call):
                continue
            fc = attr_chain(node.value.func)
            leaf = fc[-1] if fc else ""
            if leaf in _LOCK_FACTORIES:
                ci.lock_fields[tc[1]] = tc[1]
            elif leaf == "Condition":
                wrapped = None
                if node.value.args:
                    ac = attr_chain(node.value.args[0])
                    if len(ac) == 2 and ac[0] == "self":
                        wrapped = ac[1]
                cond_wraps[tc[1]] = wrapped
            elif leaf in _SELFSYNC_FACTORIES:
                ci.selfsync.add(tc[1])
        for f, wrapped in cond_wraps.items():
            # a Condition guards as the lock it wraps; a bare Condition
            # carries its own lock
            ci.lock_fields[f] = (ci.lock_fields.get(wrapped, wrapped)
                                 if wrapped else f)

    def _scan_typed(self, ci: _ClassInfo) -> None:
        init = ci.methods.get("__init__")
        params: Dict[str, str] = {}
        if init is not None:
            for a in list(init.args.args[1:]) + init.args.kwonlyargs:
                k = _annot_class(a.annotation, self.classes)
                if k:
                    params[a.arg] = k
        for node in ast.walk(ci.node):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1):
                continue
            tc = attr_chain(node.targets[0])
            if len(tc) != 2 or tc[0] != "self":
                continue
            if isinstance(node.value, ast.Call):
                fc = attr_chain(node.value.func)
                if fc and fc[-1] in self.classes:
                    ci.typed[tc[1]] = fc[-1]
            elif isinstance(node.value, ast.Name) \
                    and node.value.id in params:
                ci.typed[tc[1]] = params[node.value.id]

    # -- pass 2: entries + propagation ----------------------------------
    def _seed_entries(self) -> None:
        # spawn sites: self-method targets label their method
        for src in self.ctx.sources:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                target, label = _thread_of_spawn(node)
                if not target:
                    continue
                if target[:1] == ["self"] and len(target) == 2:
                    cls = _enclosing_class(node)
                    if cls is not None and cls.name in self.classes:
                        self.classes[cls.name].entries[target[1]] = label
                elif len(target) == 1:
                    # module-level target: determinism's resolver closes
                    # over it; module functions hold no self state, so
                    # the closure is only scanned to stay conservative
                    for fn in [f for f in src.functions()
                               if f.name == target[0]]:
                        _reachable(self.ctx, src, fn)
        for ci in self.classes.values():
            if ci.is_handler:
                for m in ci.methods:
                    if m.startswith("do_"):
                        ci.entries[m] = "http-handler"
            for (cname, m), label in DRIVER_ENTRIES.items():
                if cname == ci.name and m in ci.methods:
                    ci.entries[m] = label
            # one driver label for online spawn entries named like the
            # scheduler loop: the offline run() pin only helps if both
            # drive modes share a label
            for m, label in list(ci.entries.items()):
                if "scheduler" in label:
                    ci.entries[m] = DRIVER_THREAD
        self._mark_external()

    def _mark_external(self) -> None:
        """A class constructed anywhere outside monitored-class methods
        is externally published: its public methods are client entries."""
        inside: Dict[str, Set[Tuple[str, str]]] = {n: set()
                                                   for n in self.classes}
        outside: Set[str] = set()
        for src in self.ctx.sources:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                fc = attr_chain(node.func)
                if not fc or fc[-1] not in self.classes:
                    continue
                name = fc[-1]
                cls = _enclosing_class(node)
                unit = _enclosing_unit(node)
                if (cls is not None and cls.name in self.classes
                        and unit is not None
                        and cls.name != name):
                    inside[name].add((cls.name, unit.name))
                else:
                    outside.add(name)
        for n, ci in self.classes.items():
            # a BaseHTTPRequestHandler subclass has no visible ctor site,
            # but its construction protocol is known: the framework
            # instantiates it per connection ON the handler thread
            ci.external = (n in outside or not inside[n]) \
                and not ci.is_handler
            ci._ctor_units = inside[n]  # resolved to threads after prop.

    def _unit_key(self, cname: str, uname: str) -> Tuple[str, str]:
        return (cname, uname)

    def _edges_of(self, ci: _ClassInfo, uname: str, unit: ast.AST
                  ) -> Tuple[List[Tuple[str, str]],
                             List[Tuple[str, str, str]]]:
        """(call edges, sink-assigned nested units) of one unit."""
        edges: List[Tuple[str, str]] = []
        sinks: List[Tuple[str, str, str]] = []
        local_types = self._local_types(ci, unit)
        nested = {n.name for n in ast.iter_child_nodes(unit)
                  if isinstance(n, ast.FunctionDef)}
        for node in _shallow(unit):
            if isinstance(node, ast.Attribute):
                # any reference to a method — a call head, a property
                # access, a bound method handed out as a callback — is an
                # edge: the target runs on (at least) this unit's threads
                ac = attr_chain(node)
                if len(ac) == 2 and ac[0] == "self" \
                        and ac[1] in ci.methods:
                    edges.append((ci.name, ac[1]))
                elif (len(ac) == 3 and ac[0] == "self"
                        and ac[1] in ci.typed
                        and ac[2] in
                        self.classes[ci.typed[ac[1]]].methods):
                    edges.append((ci.typed[ac[1]], ac[2]))
                elif (len(ac) == 2 and ac[0] in local_types
                        and ac[1] in
                        self.classes[local_types[ac[0]]].methods):
                    edges.append((local_types[ac[0]], ac[1]))
            if not isinstance(node, ast.Call):
                continue
            fc = attr_chain(node.func)
            if len(fc) >= 2 and (fc[-2], fc[-1]) in SINK_CALLS:
                for arg in node.args:
                    if isinstance(arg, ast.Name) and arg.id in nested:
                        sinks.append((ci.name, f"{uname}.{arg.id}",
                                      SINK_CALLS[(fc[-2], fc[-1])]))
        # plain nested defs inherit the encloser's thread via an edge
        for n in ast.iter_child_nodes(unit):
            if isinstance(n, ast.FunctionDef):
                edges.append((ci.name, f"{uname}.{n.name}"))
        return edges, sinks

    def _local_types(self, ci: _ClassInfo, unit: ast.AST
                     ) -> Dict[str, str]:
        out: Dict[str, str] = {}
        args = getattr(unit, "args", None)
        if args is not None:
            for a in list(args.args) + list(args.kwonlyargs):
                k = _annot_class(a.annotation, self.classes)
                if k:
                    out[a.arg] = k
        for node in _shallow(unit):
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if not names:
                continue
            if isinstance(node.value, ast.Call):
                fc = attr_chain(node.value.func)
                if fc and fc[-1] in self.classes:
                    for nm in names:
                        out[nm] = fc[-1]
            else:
                vc = attr_chain(node.value)
                if (len(vc) == 2 and vc[0] == "self"
                        and vc[1] in ci.typed):
                    for nm in names:
                        out[nm] = ci.typed[vc[1]]
        return out

    def _units_of(self, ci: _ClassInfo):
        for mname, m in ci.methods.items():
            yield mname, m
            for n in ast.walk(m):
                if isinstance(n, ast.FunctionDef) and n is not m:
                    parent_unit = _enclosing_unit(n)
                    prefix = (parent_unit.name if parent_unit is not None
                              else mname)
                    yield f"{prefix}.{n.name}", n

    def _propagate_threads(self) -> None:
        threads: Dict[Tuple[str, str], Set[str]] = {}
        edges: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
        pinned: Set[Tuple[str, str]] = set()
        for ci in self.classes.values():
            for uname, unit in self._units_of(ci):
                key = self._unit_key(ci.name, uname)
                threads.setdefault(key, set())
                es, sinks = self._edges_of(ci, uname, unit)
                edges[key] = es
                for cn, un, label in sinks:
                    threads.setdefault((cn, un), set()).add(label)
                    pinned.add((cn, un))
            for m, label in ci.entries.items():
                threads[(ci.name, m)].add(label)
                pinned.add((ci.name, m))
            if ci.external:
                for m in ci.methods:
                    if m == "__init__":
                        threads[(ci.name, m)].add(CLIENT)
                    elif not m.startswith("_") \
                            and (ci.name, m) not in pinned:
                        threads[(ci.name, m)].add(CLIENT)
        outer_changed = True
        while outer_changed:
            outer_changed = False
            changed = True
            while changed:
                changed = False
                for key, es in edges.items():
                    src_threads = threads.get(key) or set()
                    if not src_threads:
                        continue
                    for callee in es:
                        if callee in pinned or callee not in threads:
                            continue
                        if callee[1] == "__init__":
                            continue  # construction is exempt
                        before = len(threads[callee])
                        threads[callee] |= src_threads
                        if len(threads[callee]) != before:
                            changed = True
                            outer_changed = True
            # internal classes inherit their constructors' threads as a
            # floor — __init__ included, so callbacks handed out during
            # construction (on_compile=outer._note_compile) carry the
            # constructing thread into their targets on the next round
            for ci in self.classes.values():
                if ci.external:
                    ci.ctor_threads = {CLIENT}
                    continue
                if ci.is_handler:
                    ci.ctor_threads = {"http-handler"}
                for cu in getattr(ci, "_ctor_units", ()):
                    ci.ctor_threads |= threads.get(cu) or set()
                for m in ci.methods:
                    if (ci.name, m) in pinned:
                        continue
                    before = len(threads[(ci.name, m)])
                    threads[(ci.name, m)] |= ci.ctor_threads
                    if len(threads[(ci.name, m)]) != before:
                        outer_changed = True
        # a unit nothing reaches still runs on SOME caller thread
        for key, ts in threads.items():
            if not ts and key[1] != "__init__":
                ts.add(CLIENT)
        self.threads = threads

    # -- pass 3: accesses + helper-held fixpoint ------------------------
    def _guard_of_with(self, ci: _ClassInfo, item: ast.withitem,
                      local_types: Dict[str, str]) -> Optional[str]:
        chain = attr_chain(item.context_expr)
        if not chain:
            return None
        if len(chain) == 2 and chain[0] == "self" \
                and chain[1] in ci.lock_fields:
            return f"{ci.name}.{ci.lock_fields[chain[1]]}"
        if len(chain) == 3 and chain[0] == "self" \
                and chain[1] in ci.typed:
            k = self.classes[ci.typed[chain[1]]]
            if chain[2] in k.lock_fields:
                return f"{k.name}.{k.lock_fields[chain[2]]}"
        if len(chain) == 2 and chain[0] in local_types:
            k = self.classes[local_types[chain[0]]]
            if chain[1] in k.lock_fields:
                return f"{k.name}.{k.lock_fields[chain[1]]}"
        return None

    def _lexical_guards(self, node: ast.AST, unit: ast.AST,
                        ci: _ClassInfo, local_types) -> frozenset:
        out: Set[str] = set()
        cur = node
        while cur is not None and cur is not unit:
            parent = getattr(cur, "_parent", None)
            if isinstance(parent, ast.With) and cur in parent.body:
                for item in parent.items:
                    g = self._guard_of_with(ci, item, local_types)
                    if g:
                        out.add(g)
            cur = parent
        return frozenset(out)

    def _field_of(self, ci: _ClassInfo, node: ast.AST,
                  local_types: Dict[str, str]
                  ) -> Optional[Tuple[str, str]]:
        """(owner class, field) named by an attribute chain rooted at
        ``self`` — directly, through one typed ref hop, or through a
        typed local (``outer = self.outer; outer.counter += 1``)."""
        chain = attr_chain(node)
        if len(chain) == 2 and chain[0] == "self":
            return ci.name, chain[1]
        if len(chain) == 3 and chain[0] == "self" \
                and chain[1] in ci.typed:
            return ci.typed[chain[1]], chain[2]
        if len(chain) == 2 and chain[0] in local_types:
            return local_types[chain[0]], chain[1]
        return None

    def _is_plain_field(self, owner: str, field: str) -> bool:
        k = self.classes[owner]
        # methods, locks, self-sync primitives and typed object refs are
        # not data fields: a call through them is dispatch, not mutation
        return (field not in k.methods
                and field not in k.lock_fields
                and field not in k.selfsync
                and field not in k.typed)

    def _collect_accesses(self) -> None:
        call_sites: Dict[Tuple[str, str],
                         List[Tuple[Tuple[str, str], frozenset]]] = {}
        raw: List[tuple] = []
        for ci in self.classes.values():
            for uname, unit in self._units_of(ci):
                ukey = self._unit_key(ci.name, uname)
                if uname == "__init__":
                    continue
                local_types = self._local_types(ci, unit)

                def note(node, owner, field, kind):
                    if not self._is_plain_field(owner, field):
                        return
                    g = self._lexical_guards(node, unit, ci, local_types)
                    raw.append((owner, field, kind, ukey, g,
                                ci.src.rel, node.lineno))

                for node in _shallow(unit):
                    if isinstance(node, (ast.Assign, ast.AugAssign,
                                         ast.AnnAssign)):
                        targets = (node.targets
                                   if isinstance(node, ast.Assign)
                                   else [node.target])
                        flat = []
                        for t in targets:
                            if isinstance(t, (ast.Tuple, ast.List)):
                                flat.extend(t.elts)
                            else:
                                flat.append(t)
                        for t in flat:
                            base = t
                            while isinstance(base, (ast.Subscript,
                                                    ast.Starred)):
                                base = base.value
                            fld = self._field_of(ci, base, local_types)
                            if fld:
                                note(t, fld[0], fld[1], "W")
                                if isinstance(node, ast.AugAssign) or \
                                        isinstance(t, ast.Subscript):
                                    note(t, fld[0], fld[1], "R")
                    elif isinstance(node, ast.Delete):
                        for t in node.targets:
                            base = t
                            while isinstance(base, ast.Subscript):
                                base = base.value
                            fld = self._field_of(ci, base, local_types)
                            if fld:
                                note(t, fld[0], fld[1], "W")
                    elif isinstance(node, ast.Call):
                        fc = attr_chain(node.func)
                        if len(fc) >= 3 and fc[-1] in _MUTATORS:
                            fld = self._field_of(
                                ci, node.func.value,  # type: ignore
                                local_types)
                            if fld:
                                note(node, fld[0], fld[1], "W")
                        # record call edges with guards for the
                        # helper-held fixpoint
                        if len(fc) == 2 and fc[0] == "self" \
                                and fc[1] in ci.methods:
                            g = self._lexical_guards(node, unit, ci,
                                                     local_types)
                            call_sites.setdefault(
                                (ci.name, fc[1]), []).append((ukey, g))
                    elif isinstance(node, ast.Attribute) \
                            and isinstance(node.ctx, ast.Load):
                        parent = getattr(node, "_parent", None)
                        if isinstance(parent, (ast.Attribute, ast.Call)) \
                                and getattr(parent, "func", None) is node:
                            continue  # method call heads handled above
                        fld = self._field_of(ci, node, local_types)
                        if fld:
                            note(node, fld[0], fld[1], "R")
        # helper-held fixpoint: a _-private helper inherits exactly the
        # locks EVERY observed call site provably holds; public methods
        # and thread entries hold nothing on entry by definition
        held: Dict[Tuple[str, str], frozenset] = {}
        private: Set[Tuple[str, str]] = set()
        for ci in self.classes.values():
            for uname, _u in self._units_of(ci):
                key = (ci.name, uname)
                held[key] = frozenset()
                base = uname.split(".")[0]
                if (base.startswith("_") and not base.startswith("__")
                        and base not in ci.entries):
                    private.add(key)
        for _ in range(3):  # enough for the repo's helper-call depth
            for callee, sites in call_sites.items():
                if callee not in private:
                    continue
                eff = None
                for caller, g in sites:
                    site = g | held.get(caller, frozenset())
                    eff = site if eff is None else (eff & site)
                held[callee] = frozenset(eff or ())
        self.entry_held = held
        self.accesses = [
            (owner, field, kind, ukey,
             guards | self.entry_held.get(ukey, frozenset()), rel, line)
            for owner, field, kind, ukey, guards, rel, line in raw]


def _short_guard(owner: str, guard: str) -> str:
    cls, _, field = guard.partition(".")
    return field if cls == owner else guard


def build_guard_map(ctx: Context) -> Tuple[Dict[str, str],
                                           List[Violation]]:
    """(field -> classification, violations). The map is the committed
    artifact; the violations are the unguarded multi-thread writes."""
    model = _Model(ctx)
    out: List[Violation] = []
    by_field: Dict[Tuple[str, str], List[tuple]] = {}
    for acc in model.accesses:
        by_field.setdefault((acc[0], acc[1]), []).append(acc)
    table: Dict[str, str] = {}
    for (owner, field), accs in sorted(by_field.items()):
        writes, reads = [], []
        for _o, _f, kind, ukey, guards, rel, line in accs:
            threads = model.threads.get(ukey) or {CLIENT}
            if threads == {INIT}:
                continue
            (writes if kind == "W" else reads).append(
                (frozenset(threads), guards, rel, line))
        key = f"{owner}.{field}"
        if not writes:
            table[key] = "unguarded-readonly"
            continue
        write_threads: Set[str] = set()
        for ts, _g, _r, _l in writes:
            write_threads |= ts
        common = None
        for _ts, g, _r, _l in writes:
            common = g if common is None else (common & g)
        common = common or frozenset()
        if common:
            table[key] = "lock:" + "+".join(
                sorted(_short_guard(owner, g) for g in common))
            continue
        if len(write_threads) <= 1:
            t = next(iter(write_threads)) if write_threads else CLIENT
            read_threads: Set[str] = set()
            for ts, _g, _r, _l in reads:
                read_threads |= ts
            if read_threads - write_threads:
                table[key] = f"single-writer({t})"
            else:
                table[key] = f"thread-confined({t})"
            continue
        # >= 2 writing threads, empty guard intersection: a race unless
        # every bare write site carries an allow[races] marker
        bare = [(rel, line) for _ts, g, rel, line in writes if not g]
        sites = bare or [(rel, line) for _ts, _g, rel, line in writes]
        reasons = []
        unmarked = []
        for rel, line in sorted(set(sites)):
            src = next((s for s in ctx.sources if s.rel == rel), None)
            ln = None if src is None else _allow_line(src, "races", line)
            if ln is not None:
                reasons.append(src.allows[ln]["races"])
            else:
                unmarked.append((rel, line))
        if not unmarked and reasons:
            table[key] = f"allow({reasons[0]})"
        else:
            table[key] = "UNGUARDED"
        threads_s = "+".join(sorted(write_threads))
        for rel, line in sorted(set(sites)):
            out.append(Violation(
                "races", rel, line,
                f"field {key} is written from threads [{threads_s}] "
                f"with no common lock — guard every write with one "
                f"shared lock, or allow-mark the benign pattern "
                f"(# heat-tpu: allow[races] why)"))
    return table, out


def guards_path(ctx: Context):
    return ctx.schema_registry.with_name("guards.json")


def load_guard_map(path) -> Optional[dict]:
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


def write_guard_map(path, table: Dict[str, str]) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = {"version": 1,
               "comment": "committed cross-thread guard map — regenerate "
                          "with `heat-tpu check --update-schemas` and "
                          "review the diff (TROUBLESHOOTING.md: guard-map "
                          "drift on an intentional new field)",
               "fields": table}
    path.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")


@register("races",
          "per-field lockset/guard-map analysis over the thread-shared "
          "objects; unguarded multi-thread writes fail, classifications "
          "gated against schemas/guards.json")
def check(ctx: Context) -> List[Violation]:
    table, out = build_guard_map(ctx)
    path = guards_path(ctx)
    if ctx.update_schemas:
        write_guard_map(path, table)
        return out
    if not table and not path.exists():
        # a tree with no thread-shared classes needs no committed map
        return out
    committed = load_guard_map(path)
    if committed is None:
        out.append(Violation(
            "races", path.name if not path.exists() else str(path), 0,
            f"guard map {path} missing/unreadable — generate it with "
            f"`heat-tpu check --update-schemas` and commit it"))
        return out
    old = committed.get("fields", {})
    rel = "analysis/schemas/guards.json"
    for key in sorted(set(old) | set(table)):
        if key not in table:
            out.append(Violation(
                "races", rel, 0,
                f"guard-map drift: field {key!r} is committed but no "
                f"longer observed — if intentional, run `heat-tpu check "
                f"--update-schemas` and commit the diff"))
        elif key not in old:
            out.append(Violation(
                "races", rel, 0,
                f"guard-map drift: new shared field {key!r} "
                f"(classified {table[key]!r}) not in the committed map "
                f"— run `heat-tpu check --update-schemas` and commit "
                f"the diff so the guard change is reviewed"))
        elif old[key] != table[key]:
            out.append(Violation(
                "races", rel, 0,
                f"guard-map drift: field {key!r} changed "
                f"{old[key]!r} -> {table[key]!r} — a guard change is a "
                f"concurrency-contract change; if intentional, "
                f"`heat-tpu check --update-schemas` and commit the diff"))
    return out
