"""Rule family 5 — **record-schema registry** (``record-schema``).

Every structured JSON line this stack emits funnels through ONE emitter
(``runtime/logging.json_record``), and consumers — the usage CLI, the
labs, the flight-recorder postmortems, any operator's ``grep`` — parse
those records by key. PR 7 established the contract that the record
schema "never flickers" (trace ids minted even with tracing off, usage
stamps present even with the observatory off); until now it held because
every author remembered. This rule makes it mechanical:

1. **Extraction**: walk every ``json_record(...)`` call site in the
   package. The event name must be a string literal (a dynamic event
   name is unauditable and is itself a violation). Explicit keyword
   arguments contribute their names; a ``**star`` argument is resolved
   statically — a local dict-literal binding, a registered producer
   function whose ``return {...}`` literals define the keys
   (``BurnMonitor.note``, ``MemWatermark.note``), or the scheduler's
   ``serve_request`` record shape (the ``submit()`` literal plus every
   ``rec["key"] = ...`` store in ``serve/scheduler.py``, minus the
   ``_``-internal keys and the field payload ``T`` that
   ``Engine._public`` strips). A star argument the resolver cannot
   attribute is a violation: every emission site must be statically
   accountable or explicitly registered in ``STAR_RESOLVERS`` below.
2. **The registry**: the union of keys per event is compared against the
   committed ``heat_tpu/analysis/schemas/records.json``. Any drift —
   new event, dropped event, added key, removed key — fails
   ``heat-tpu check`` with the exact delta. Intentional changes are a
   two-step: ``heat-tpu check --update-schemas`` rewrites the registry,
   and the registry diff rides the same PR as the code change — schema
   changes get reviewed, never slipped.
"""

from __future__ import annotations

import ast
import json
from typing import Dict, List, Optional, Set, Tuple

from .core import (Context, Violation, attr_chain, enclosing_function,
                   register)

# (file suffix, enclosing function name, star-arg name) -> producer spec:
# ("returns", file suffix, function qualname) = keys of that function's
# dict-literal returns; ("serve-record",) = the scheduler record shape.
STAR_RESOLVERS: Dict[Tuple[str, str, str], tuple] = {
    ("serve/scheduler.py", "_emit", "snap"): ("serve-record",),
    ("serve/scheduler.py", "_emit", "alert"):
        ("returns", "runtime/prof.py", "BurnMonitor.note"),
    ("serve/scheduler.py", "_mem_warn", "warn"):
        ("returns", "runtime/prof.py", "MemWatermark.note"),
}


def _const_keys(d: ast.Dict) -> Optional[Set[str]]:
    keys = set()
    for k in d.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.add(k.value)
        else:
            return None   # **spread or computed key: not a literal shape
    return keys


def _return_dict_keys(ctx: Context, file_suffix: str, qualname: str
                      ) -> Optional[Set[str]]:
    src = ctx.source(file_suffix)
    if src is None:
        return None
    for fn in src.functions():
        if getattr(fn, "_qualname", fn.name) == qualname:
            keys: Set[str] = set()
            found = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and isinstance(
                        node.value, ast.Dict):
                    k = _const_keys(node.value)
                    if k is not None:
                        keys |= k
                        found = True
            return keys if found else None
    return None


def serve_record_keys(ctx: Context) -> Optional[Set[str]]:
    """The ``serve_request`` record shape, derived from scheduler.py the
    way the engine actually builds it: the ``submit()`` dict literal plus
    every constant-key ``rec[...] = `` store anywhere in the module,
    minus ``_``-prefixed internals and the field payload ``T`` (exactly
    what ``Engine._public`` strips before emission)."""
    src = ctx.source("serve/scheduler.py")
    if src is None:
        return None
    keys: Set[str] = set()
    for node in ast.walk(src.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1):
            t = node.targets[0]
            if (isinstance(t, ast.Name) and t.id == "rec"
                    and isinstance(node.value, ast.Dict)):
                k = _const_keys(node.value)
                if k:
                    keys |= k
            if (isinstance(t, ast.Subscript) and isinstance(
                    t.value, ast.Name) and t.value.id == "rec"
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)):
                keys.add(t.slice.value)
    if not keys:
        return None
    return {k for k in keys if not k.startswith("_") and k != "T"}


def _local_dict_keys(fn: ast.FunctionDef, name: str) -> Optional[Set[str]]:
    """Keys of a star-arg bound from a dict literal inside the enclosing
    function, plus any ``name["k"] = ...`` stores there."""
    keys: Optional[Set[str]] = None
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1):
            t = node.targets[0]
            if (isinstance(t, ast.Name) and t.id == name
                    and isinstance(node.value, ast.Dict)):
                k = _const_keys(node.value)
                if k is not None:
                    keys = (keys or set()) | k
            if (isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name) and t.value.id == name
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)):
                keys = (keys or set()) | {t.slice.value}
    return keys


def extract_schemas(ctx: Context) -> Tuple[Dict[str, dict],
                                           List[Violation]]:
    """(event -> {"keys": sorted, "sites": n}, violations)."""
    events: Dict[str, Set[str]] = {}
    sites: Dict[str, int] = {}
    out: List[Violation] = []
    for src in ctx.sources:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain or chain[-1] != "json_record":
                continue
            fn = enclosing_function(node)
            if fn is not None and fn.name == "json_record":
                continue   # the emitter's own definition/recursion
            if not node.args or not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                out.append(Violation(
                    "record-schema", src.rel, node.lineno,
                    "json_record with a non-literal event name — every "
                    "record stream must be statically enumerable"))
                continue
            event = node.args[0].value
            keys: Set[str] = set()
            ok = True
            for kw in node.keywords:
                if kw.arg is not None:
                    keys.add(kw.arg)
                    continue
                star = kw.value
                sname = star.id if isinstance(star, ast.Name) else None
                resolved = None
                if sname and fn is not None:
                    resolved = _local_dict_keys(fn, sname)
                    if resolved is None:
                        for (sfx, fname, aname), spec in \
                                STAR_RESOLVERS.items():
                            if (src.rel.endswith(sfx)
                                    and fn.name == fname
                                    and sname == aname):
                                if spec[0] == "serve-record":
                                    resolved = serve_record_keys(ctx)
                                elif spec[0] == "returns":
                                    resolved = _return_dict_keys(
                                        ctx, spec[1], spec[2])
                                break
                if resolved is None:
                    ok = False
                    out.append(Violation(
                        "record-schema", src.rel, node.lineno,
                        f"unresolvable **{sname or '<expr>'} in "
                        f"json_record({event!r}, ...) — bind it from a "
                        f"dict literal, or register the producer in "
                        f"analysis/schema.py STAR_RESOLVERS so the "
                        f"registry stays exact"))
                else:
                    keys |= resolved
            if not ok:
                continue
            events[event] = events.get(event, set()) | keys
            sites[event] = sites.get(event, 0) + 1
    table = {ev: {"keys": sorted(ks), "sites": sites[ev]}
             for ev, ks in sorted(events.items())}
    return table, out


def load_registry(path) -> Optional[dict]:
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


def write_registry(path, table: Dict[str, dict]) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = {"version": 1,
               "comment": "committed record-schema registry — regenerate "
                          "with `heat-tpu check --update-schemas` and "
                          "review the diff (TROUBLESHOOTING.md: "
                          "intentional schema drift)",
               "events": table}
    path.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")


@register("record-schema",
          "every json_record site statically resolved; key sets gated "
          "against the committed schemas/records.json")
def check(ctx: Context) -> List[Violation]:
    table, out = extract_schemas(ctx)
    reg_path = ctx.schema_registry
    if ctx.update_schemas:
        write_registry(reg_path, table)
        return out
    committed = load_registry(reg_path)
    if committed is None:
        out.append(Violation(
            "record-schema",
            reg_path.name if not reg_path.exists() else str(reg_path),
            0,
            f"schema registry {reg_path} missing/unreadable — generate "
            f"it with `heat-tpu check --update-schemas` and commit it"))
        return out
    old = committed.get("events", {})
    for ev in sorted(set(old) | set(table)):
        if ev not in table:
            out.append(Violation(
                "record-schema", "analysis/schemas/records.json", 0,
                f"event {ev!r} is in the committed registry but no "
                f"longer emitted — if intentional, run `heat-tpu check "
                f"--update-schemas` and commit the registry diff"))
        elif ev not in old:
            out.append(Violation(
                "record-schema", "analysis/schemas/records.json", 0,
                f"new record event {ev!r} (keys "
                f"{table[ev]['keys']}) not in the committed registry — "
                f"run `heat-tpu check --update-schemas` and commit the "
                f"diff so the schema change is reviewed"))
        else:
            added = sorted(set(table[ev]["keys"]) - set(old[ev]["keys"]))
            removed = sorted(set(old[ev]["keys"]) - set(table[ev]["keys"]))
            if added or removed:
                out.append(Violation(
                    "record-schema", "analysis/schemas/records.json", 0,
                    f"key-set drift for event {ev!r}: "
                    + (f"added {added} " if added else "")
                    + (f"removed {removed} " if removed else "")
                    + "— consumers parse these records by key; if "
                      "intentional, `heat-tpu check --update-schemas` "
                      "and commit the registry diff"))
    return out
