"""Rule family 2 — **lock discipline** (``lock-discipline``).

PR 8 documented the serving stack's lock order — gateway < engine <
observatory (prof/trace instruments) — and "enforced it by construction":
observatory instruments carry their own locks and never take the engine
lock, so a /metrics scrape can never deadlock the boundary hot path.
Before the pod-scale router multiplies thread and lock count, that
convention becomes machine-checked, twice over:

- **statically, here**: extract every ``with <lock>`` site across
  ``serve/`` and ``runtime/``, classify each lock expression into its
  rank (the table below mirrors ``runtime/debug.LOCK_RANKS``), and
  assert (a) no ``with`` block ever *nests* a lower-or-equal-rank
  acquisition inside a higher one, and (b) while the **engine lock** is
  held, the block performs no file/stream I/O, no device fetches, and no
  observatory-entry calls — except at explicitly allow-marked sanctioned
  seams (``Engine._emit`` is the one: the engine lock IS the
  serialization point for record JSON lines, and its
  ``prof.note_terminal`` call is the documented engine→observatory
  direction);
- **dynamically** via the opt-in watchdog (``HEAT_TPU_LOCKCHECK=1``,
  ``runtime/debug.make_lock``) that tracks per-thread held-lock stacks at
  runtime and raises on the acquisition that inverts the order — run
  under the chaos suite, where the fault-injected paths (quarantine,
  rollback, watchdog, flight dump) all cross threads.

The static half is deliberately conservative: it sees lexical nesting and
a curated map of lock-taking callables, not aliasing. What it cannot see,
the dynamic watchdog does; what the watchdog only sees when a path runs,
this rule sees on every ``heat-tpu check``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .core import Context, Violation, attr_chain, dotted, register

# rank table (mirrors runtime/debug.LOCK_RANKS): the fleet router is
# outermost in every request path — fleet < gateway < engine < writer <
# observatory
RANKS = {"fleet": -10, "gateway": 0, "engine": 10, "writer": 20,
         "observatory": 30}

# lock-expression classification: (path suffix the file must match,
# attribute-chain suffix of the with-item expression) -> rank name.
# ``self._lock``/``self._cond`` mean different locks in different files —
# the file scopes the meaning.
LOCK_EXPRS: List[Tuple[str, Tuple[str, ...], str]] = [
    ("serve/scheduler.py", ("_lock",), "engine"),
    ("serve/scheduler.py", ("_cond",), "engine"),
    ("serve/gateway.py", ("_drain_lock",), "gateway"),
    ("fleet/router.py", ("_lock",), "fleet"),
    ("fleet/registry.py", ("_lock",), "fleet"),
    ("runtime/prof.py", ("_lock",), "observatory"),
    ("runtime/prof.py", ("_COMPILE_LOG_LOCK",), "observatory"),
    ("runtime/trace.py", ("_lock",), "observatory"),
    ("runtime/trace.py", ("_GLOBAL_LOCK",), "observatory"),
]

# callables known to ACQUIRE a lock when invoked (attr-chain suffixes).
# Used for nesting edges the lexical scan cannot see.
ACQUIRING_CALLS: Dict[Tuple[str, ...], str] = {
    ("prof", "note_terminal"): "observatory",
    ("prof", "observe_chunk"): "observatory",
    ("prof", "maybe_sample_memory"): "observatory",
    ("prof", "summary"): "observatory",
    ("ledger", "add"): "observatory",
    ("burn", "note"): "observatory",
    ("hist", "observe"): "observatory",
    # engine-lock-taking entry points: calling these while holding an
    # observatory lock is the forbidden reverse direction
    ("submit",): "engine",
    ("poll",): "engine",
    ("queue_depths",): "engine",
    ("begin_drain",): "engine",
}

# I/O and device calls forbidden while the ENGINE lock is held (the
# fetch would extend the lock's critical section across a device fence;
# the I/O would serialize disk latency into admission).
_IO_CALLS = {"open", "print", "master_print", "json_record",
             "write_text", "write_bytes", "savez", "savez_compressed",
             "save", "flush", "mkdir", "rename", "unlink"}
_DEVICE_CALLS = {"host_fetch", "block_until_ready", "item", "device_get",
                 "asarray"}


def _lock_rank(src_rel: str, expr: ast.AST) -> Optional[str]:
    chain = tuple(attr_chain(expr))
    if not chain:
        return None
    for suffix, names, rank in LOCK_EXPRS:
        if src_rel.endswith(suffix) and chain[-len(names):] == tuple(names):
            return rank
    return None


def _with_lock_items(src, node: ast.With):
    for item in node.items:
        rank = _lock_rank(src.rel, item.context_expr)
        if rank is not None:
            yield rank


def _call_rank(node: ast.Call) -> Optional[str]:
    chain = tuple(attr_chain(node.func))
    if not chain:
        return None
    for suffix, rank in ACQUIRING_CALLS.items():
        if chain[-len(suffix):] == suffix:
            return rank
    return None


@register("lock-discipline",
          "gateway < engine < observatory order; no I/O/device work or "
          "unsanctioned observatory entry under the engine lock")
def check(ctx: Context) -> List[Violation]:
    out: List[Violation] = []
    for src in ctx.sources:
        if not ("serve/" in src.rel or "runtime/" in src.rel
                or "fleet/" in src.rel):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.With):
                continue
            ranks = list(_with_lock_items(src, node))
            if not ranks:
                continue
            outer_rank = max(RANKS[r] for r in ranks)
            outer_name = max(ranks, key=lambda r: RANKS[r])
            for inner in ast.walk(node):
                if inner is node:
                    continue
                if isinstance(inner, ast.With):
                    for irank in _with_lock_items(src, inner):
                        if RANKS[irank] <= outer_rank:
                            out.append(Violation(
                                "lock-discipline", src.rel, inner.lineno,
                                f"nested `with` acquires {irank!r} lock "
                                f"(rank {RANKS[irank]}) while holding "
                                f"{outer_name!r} lock (rank {outer_rank}) "
                                f"— documented order is gateway < engine "
                                f"< observatory, strictly"))
                if isinstance(inner, ast.Call):
                    crank = _call_rank(inner)
                    if crank is not None and RANKS[crank] <= outer_rank:
                        out.append(Violation(
                            "lock-discipline", src.rel, inner.lineno,
                            f"call `{dotted(inner.func)}` acquires the "
                            f"{crank!r} lock inside a {outer_name!r}-lock "
                            f"block — the reverse of the documented "
                            f"order (deadlock seed)"))
                    name = (inner.func.attr
                            if isinstance(inner.func, ast.Attribute)
                            else inner.func.id
                            if isinstance(inner.func, ast.Name) else "")
                    if outer_name == "engine":
                        if name in _IO_CALLS:
                            out.append(Violation(
                                "lock-discipline", src.rel, inner.lineno,
                                f"I/O call `{dotted(inner.func) or name}` "
                                f"while the engine lock is held — disk/"
                                f"stream latency serializes into "
                                f"admission and the boundary hot path"))
                        elif name in _DEVICE_CALLS:
                            out.append(Violation(
                                "lock-discipline", src.rel, inner.lineno,
                                f"device call `{dotted(inner.func) or name}` "
                                f"while the engine lock is held — a device "
                                f"fence inside the admission critical "
                                f"section stalls every submitting thread"))
                        elif crank == "observatory":
                            out.append(Violation(
                                "lock-discipline", src.rel, inner.lineno,
                                f"observatory entry `{dotted(inner.func)}` "
                                f"while the engine lock is held — only "
                                f"the allow-marked sanctioned seam "
                                f"(Engine._emit) may cross engine->"
                                f"observatory under the lock"))
    return out
