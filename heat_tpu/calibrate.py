"""``heat-tpu calibrate`` — fit this chip's ChipModel from on-device sweeps.

VERDICT r4 #6: the non-v5e rows in ``machine._CHIPS`` scale v5e's *fitted
VPU* rates by public *peak MXU TFLOP* ratios — a crude proxy the file
admits to. The day a v5p/v6e is attached, the planner runs on a guess.
This command closes that gap: a ~minutes-long sweep measures

- ``hbm_bytes_per_s``  — device STREAM (x + 1 over a large buffer: one
  read + one write per element), overhead-cancelled by the two-point
  protocol (``runtime/timing.py::two_point_rate``);
- ``vpu_ops_per_s``    — the 2D thin-band stencil rate at the planner's
  own geometry, inverted through ``_plan_2d``'s additive cost model;
- ``ops_rate_3d``      — ditto through ``_plan_3d``'s model at 512^3,

and emits a provenance-stamped JSON the machine table consumes directly
(``HEAT_CHIP_CALIBRATION=<path>``), so a freshly attached chip goes from
spec-proxy to fitted without editing code. VMEM ceilings are NOT fitted
(they are compiler limits, validated separately by
``benchmarks/topology_validate.py``'s AOT RESOURCE_EXHAUSTED checks) and
are carried over from the table entry for the detected chip class.

On a non-TPU platform the sweep still runs (tiny shapes, interpret-mode
kernels) so the harness is testable anywhere, but the output is labeled
``trustworthy: false`` and ``calibrated`` stays False — interpret-mode
rates say nothing about any chip.

Reference parity: the reference has no analog (constants live in its
kernel launch configs, e.g. the fixed 16x16 blocks of
fortran/cuda_kernel/heat.F90); this is the price of having a planner.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Optional


def _jnp():
    import jax.numpy as jnp

    return jnp


def measure_hbm(mib: int = 256, repeats: int = 3) -> dict:
    """STREAM-style device bandwidth: jit(x + 1) moves itemsize bytes in
    and out per element; the two-point protocol cancels dispatch/sync
    overhead (decisive on the tunneled platform)."""
    import jax

    from .runtime.timing import two_point_rate

    jnp = _jnp()
    n = mib * (1 << 20) // 4
    x = jnp.zeros((n,), jnp.float32)
    f = jax.jit(lambda a: a + 1.0, donate_argnums=0)
    bytes_per_call = 2.0 * n * 4
    rate, raw = two_point_rate(lambda t: f(t), x, bytes_per_call,
                               repeats=repeats)
    return {"hbm_bytes_per_s": rate, "hbm_bytes_per_s_raw": raw,
            "buffer_mib": mib}


def _solve_rate(cfg, repeats: int = 2) -> float:
    """points/s for ``cfg`` via the framework's own solve path, two-point
    corrected (falls back to the raw rate below the protocol's noise
    floor, which two_point_rate handles itself)."""
    from .backends import solve

    res = solve(cfg, fetch=False, warm_exec=True,
                two_point_repeats=repeats)
    return res.timing.points_per_s_two_point or res.timing.points_per_s


def _invert_rate(cost_at_rate, t_pp: float,
                 lo: float = 1e8, hi: float = 1e16) -> Optional[float]:
    """Find the compute rate at which the (monotone-decreasing-in-rate)
    cost model predicts the measured t_pp. Bisection against the
    planner's OWN cost function — no formula copy to drift. None when no
    rate in [lo, hi] explains the measurement (e.g. measured faster than
    the model's bandwidth floor: the model is wrong there, don't fit)."""
    if not (cost_at_rate(hi) < t_pp < cost_at_rate(lo)):
        return None
    for _ in range(200):
        mid = (lo * hi) ** 0.5  # geometric: the range spans 8 decades
        if cost_at_rate(mid) > t_pp:
            lo = mid
        else:
            hi = mid
    return (lo * hi) ** 0.5


def fit_vpu_2d(t_pp: float, shape, dtype_str: str, ksteps: int,
               chip_with_hbm) -> Optional[float]:
    """Fit ``vpu_ops_per_s`` by inverting ``cost_thin_2d`` — the exact
    function ``_plan_2d`` ranks with — at the chunk depth the planner
    chose for this shape. None for a coltiled plan (not the calibration
    target) or an uninvertible measurement."""
    import dataclasses as dc

    from .ops import pallas_stencil as ps

    plan = ps._plan_2d(tuple(shape), dtype_str, ksteps)
    if plan is None or plan[0] != "thin":
        return None
    kchunk = plan[1]
    n_pad = ps._round_up(max(shape[1], 128), 128)
    return _invert_rate(
        lambda v: ps.cost_thin_2d(
            n_pad, kchunk, dtype_str,
            dc.replace(chip_with_hbm, vpu_ops_per_s=v)),
        t_pp)


def fit_ops_3d(t_pp: float, shape, dtype_str: str, ksteps: int,
               chip_with_hbm) -> Optional[float]:
    """Fit ``ops_rate_3d`` by inverting ``cost_3d`` (shared with
    ``_plan_3d``) at its chosen (R, M, k), de-rated by the alignment-
    padding waste factor exactly as the planner charges it."""
    import dataclasses as dc

    from .ops import pallas_stencil as ps

    plan = ps._plan_3d(tuple(shape), dtype_str, ksteps)
    if plan is None:
        return None
    (m_pad, mid_pad, _n_pad), R, M, k = plan
    pad = m_pad * mid_pad / max(shape[0] * shape[1], 1)
    return _invert_rate(
        lambda v: ps.cost_3d(R, M, k, dtype_str,
                             dc.replace(chip_with_hbm, ops_rate_3d=v)) * pad,
        t_pp)


def run(out_path: str, quick: bool = False) -> dict:
    """The full calibration sweep. Writes ``out_path`` (JSON) and returns
    the record."""
    import jax

    from . import machine
    from .config import HeatConfig

    platform = jax.default_backend()
    on_tpu = platform == "tpu"
    kind = jax.devices()[0].device_kind
    base = machine.classify(kind) if on_tpu else machine._DEFAULT

    # shapes: flagship-representative on a real chip; tiny everywhere else
    # (interpret-mode pallas at 4096^2 would take hours on a CPU)
    n2d = 4096 if on_tpu and not quick else 256
    n3d = 512 if on_tpu and not quick else 32
    steps = 256 if on_tpu and not quick else 16
    hbm_mib = 256 if on_tpu else 8

    rec: dict = {"ts": time.time(), "platform": platform,
                 "device_kind": kind, "chip_class": base.name,
                 "trustworthy": bool(on_tpu),
                 "params": {"n2d": n2d, "n3d": n3d, "steps": steps,
                            "hbm_mib": hbm_mib}}

    print(f"calibrate: platform={platform} device={kind!r} "
          f"(chip class {base.label})")
    stream = measure_hbm(mib=hbm_mib)
    rec["stream"] = stream
    hbm = stream["hbm_bytes_per_s"]
    print(f"  HBM stream: {hbm / 1e9:.1f} GB/s")

    chip_meas = dataclasses.replace(base, hbm_bytes_per_s=float(hbm))
    k2 = 16
    cfg2 = HeatConfig(n=n2d, ntime=steps, dtype="float32",
                      backend="pallas", fuse_steps=k2)
    rate2 = _solve_rate(cfg2)
    t_pp2 = 1.0 / rate2
    vpu = fit_vpu_2d(t_pp2, (n2d, n2d), "float32", k2, chip_meas)
    rec["sweep_2d"] = {"n": n2d, "fuse": k2, "points_per_s": rate2,
                       "vpu_ops_per_s_fit": vpu}
    print(f"  2D {n2d}^2 fuse={k2}: {rate2:.3e} pts/s -> vpu "
          f"{vpu / 1e12 if vpu else float('nan'):.2f} Tops/s")

    k3 = 8
    cfg3 = HeatConfig(n=n3d, ndim=3, ntime=steps, dtype="float32",
                      backend="pallas", fuse_steps=k3)
    rate3 = _solve_rate(cfg3)
    ops3 = fit_ops_3d(1.0 / rate3, (n3d,) * 3, "float32", k3, chip_meas)
    rec["sweep_3d"] = {"n": n3d, "fuse": k3, "points_per_s": rate3,
                       "ops_rate_3d_fit": ops3}
    print(f"  3D {n3d}^3 fuse={k3}: {rate3:.3e} pts/s -> ops3d "
          f"{ops3 / 1e12 if ops3 else float('nan'):.2f} Tops/s")

    fitted = dataclasses.asdict(dataclasses.replace(
        base,
        name=base.name if on_tpu else f"{base.name}-proxy",
        hbm_bytes_per_s=float(hbm),
        vpu_ops_per_s=float(vpu) if vpu else base.vpu_ops_per_s,
        ops_rate_3d=float(ops3) if ops3 else base.ops_rate_3d,
        calibrated=bool(on_tpu and vpu and ops3)))
    rec["chip_model"] = fitted
    rec["fit_complete"] = bool(vpu and ops3)
    if on_tpu:
        # reproduction check against the shipped table for a KNOWN chip:
        # the acceptance bar is "reproduces the shipped constants within
        # tolerance" (VERDICT r4 #6) — report the ratios so drift is a
        # number, not a feeling
        rec["vs_table"] = {
            "hbm_ratio": hbm / base.hbm_bytes_per_s,
            "vpu_ratio": (vpu / base.vpu_ops_per_s) if vpu else None,
            "ops3d_ratio": (ops3 / base.ops_rate_3d) if ops3 else None,
        }
        print("  vs shipped table: " + ", ".join(
            f"{k}={v:.2f}x" if v else f"{k}=n/a"
            for k, v in rec["vs_table"].items()))
    else:
        print("  NOT TRUSTWORTHY: interpret-mode rates on a non-TPU "
              "platform say nothing about any chip (harness check only)")

    with open(str(out_path) + ".tmp", "w") as f:
        json.dump(rec, f, indent=2, default=float)
    import os

    os.replace(str(out_path) + ".tmp", out_path)
    print(f"wrote {out_path}")
    print(f"use it: HEAT_CHIP_CALIBRATION={out_path} heat-tpu run ...")
    return rec
