"""``heat-tpu calibrate`` — fit this chip's ChipModel from on-device sweeps.

VERDICT r4 #6: the non-v5e rows in ``machine._CHIPS`` scale v5e's *fitted
VPU* rates by public *peak MXU TFLOP* ratios — a crude proxy the file
admits to. The day a v5p/v6e is attached, the planner runs on a guess.
This command closes that gap: a ~minutes-long sweep measures

- ``hbm_bytes_per_s``  — device STREAM: a ``fori_loop`` of read+write
  sweeps over a large buffer (many passes per dispatch so the two-point
  correction in ``runtime/timing.py::two_point_rate`` clears its noise
  floor on the tunneled platform — see ``measure_hbm``);
- ``vpu_ops_per_s``    — the 2D thin-band stencil rate at the planner's
  own geometry, inverted through ``_plan_2d``'s additive cost model;
- ``ops_rate_3d``      — ditto through ``_plan_3d``'s model at 512^3,

and emits a provenance-stamped JSON the machine table consumes directly
(``HEAT_CHIP_CALIBRATION=<path>``), so a freshly attached chip goes from
spec-proxy to fitted without editing code. VMEM ceilings are NOT fitted
(they are compiler limits, validated separately by
``benchmarks/topology_validate.py``'s AOT RESOURCE_EXHAUSTED checks) and
are carried over from the table entry for the detected chip class.

On a non-TPU platform the sweep still runs (tiny shapes, interpret-mode
kernels) so the harness is testable anywhere, but the output is labeled
``trustworthy: false`` and ``calibrated`` stays False — interpret-mode
rates say nothing about any chip.

Reference parity: the reference has no analog (constants live in its
kernel launch configs, e.g. the fixed 16x16 blocks of
fortran/cuda_kernel/heat.F90); this is the price of having a planner.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Optional


def _jnp():
    import jax.numpy as jnp

    return jnp


def measure_hbm(mib: int = 256, repeats: int = 3, passes: int = 256) -> dict:
    """STREAM-style device bandwidth: each jitted call runs ``passes``
    read+write sweeps of the buffer via ``lax.fori_loop`` (a loop, not an
    unrolled chain — XLA reassociates ``(a+1)+1`` into ``a+2`` and would
    fold an unrolled version into one pass).

    ``passes`` exists because of the tunneled platform's ~0.15 s fixed
    dispatch cost: a SINGLE 256 MiB pass is ~0.65 ms of chip time, so
    T2-T1 sits far below the two-point noise floor and the protocol
    (correctly) falls back to the raw dispatch-dominated rate — 4.2 GB/s
    on a ~819 GB/s chip, first on-chip calibrate of round 5. With 256
    passes (~0.17 s of chip time per call) T2-T1 ~ 2.6x the 20% floor
    even at the docstring dispatch estimate — 64 passes would clear it
    by only ~8%, inside dispatch jitter (review r5)."""
    import jax
    from jax import lax

    from .runtime.timing import two_point_rate

    jnp = _jnp()
    n = mib * (1 << 20) // 4
    x = jnp.zeros((n,), jnp.float32)
    f = jax.jit(
        lambda a: lax.fori_loop(0, passes, lambda i, t: t + 1.0, a),
        donate_argnums=0)
    bytes_per_call = 2.0 * n * 4 * passes
    res = two_point_rate(lambda t: f(t), x, bytes_per_call,
                         repeats=repeats)
    rate, raw = res
    return {"hbm_bytes_per_s": rate, "hbm_bytes_per_s_raw": raw,
            "floor_fallback": res.fell_back,
            "buffer_mib": mib, "passes": passes}


def _solve_rate(cfg, repeats: int = 2) -> tuple[float, bool]:
    """(points/s, overhead_dominated) for ``cfg`` via the framework's own
    solve path. ``overhead_dominated`` is True when the two-point
    correction hit its noise floor (or didn't run) and the rate is the
    raw dispatch-laden one — a fit from such a rate would bake tunnel
    dispatch into a chip constant, the same poisoning the HBM floor
    guard refuses (review r5)."""
    from .backends import solve

    res = solve(cfg, fetch=False, warm_exec=True,
                two_point_repeats=repeats)
    t = res.timing
    if t.points_per_s_two_point and t.two_point_fell_back is False:
        return t.points_per_s_two_point, False
    return (t.points_per_s_two_point or t.points_per_s), True


def _invert_rate(cost_at_rate, t_pp: float,
                 lo: float = 1e8, hi: float = 1e16) -> Optional[float]:
    """Find the compute rate at which the (monotone-decreasing-in-rate)
    cost model predicts the measured t_pp. Bisection against the
    planner's OWN cost function — no formula copy to drift. None when no
    rate in [lo, hi] explains the measurement (e.g. measured faster than
    the model's bandwidth floor: the model is wrong there, don't fit)."""
    if not (cost_at_rate(hi) < t_pp < cost_at_rate(lo)):
        return None
    for _ in range(200):
        mid = (lo * hi) ** 0.5  # geometric: the range spans 8 decades
        if cost_at_rate(mid) > t_pp:
            lo = mid
        else:
            hi = mid
    return (lo * hi) ** 0.5


def fit_vpu_2d(t_pp: float, shape, dtype_str: str, ksteps: int,
               chip_with_hbm) -> Optional[float]:
    """Fit ``vpu_ops_per_s`` by inverting ``cost_thin_2d`` — the exact
    function ``_plan_2d`` ranks with — at the chunk depth the planner
    chose for this shape. None for a coltiled plan (not the calibration
    target) or an uninvertible measurement."""
    import dataclasses as dc

    from .ops import pallas_stencil as ps

    plan = ps._plan_2d(tuple(shape), dtype_str, ksteps)
    if plan is None or plan[0] != "thin":
        return None
    kchunk = plan[1]
    n_pad = ps._round_up(max(shape[1], 128), 128)
    return _invert_rate(
        lambda v: ps.cost_thin_2d(
            n_pad, kchunk, dtype_str,
            dc.replace(chip_with_hbm, vpu_ops_per_s=v)),
        t_pp)


def fit_ops_3d(t_pp: float, shape, dtype_str: str, ksteps: int,
               chip_with_hbm) -> Optional[float]:
    """Fit ``ops_rate_3d`` by inverting ``cost_3d`` (shared with
    ``_plan_3d``) at its chosen (R, M, k), de-rated by the alignment-
    padding waste factor exactly as the planner charges it."""
    import dataclasses as dc

    from .ops import pallas_stencil as ps

    plan = ps._plan_3d(tuple(shape), dtype_str, ksteps)
    if plan is None:
        return None
    (m_pad, mid_pad, _n_pad), R, M, k = plan
    pad = m_pad * mid_pad / max(shape[0] * shape[1], 1)
    return _invert_rate(
        lambda v: ps.cost_3d(R, M, k, dtype_str,
                             dc.replace(chip_with_hbm, ops_rate_3d=v)) * pad,
        t_pp)


def run(out_path: str, quick: bool = False) -> dict:
    """The full calibration sweep. Writes ``out_path`` (JSON) and returns
    the record."""
    import jax

    from . import machine
    from .config import HeatConfig

    platform = jax.default_backend()
    on_tpu = platform == "tpu"
    kind = jax.devices()[0].device_kind
    base = machine.classify(kind) if on_tpu else machine._DEFAULT

    # shapes: flagship-representative on a real chip; tiny everywhere else
    # (interpret-mode pallas at 4096^2 would take hours on a CPU). Step
    # counts are sized so the solve is SECONDS of chip time — the
    # tunnel's ~0.15 s dispatch cost made 256-step probes read 5x low
    # (overhead-dominated, two-point floor fallback; first on-chip
    # calibrate of round 5), which poisoned the fit bracket.
    n2d = 4096 if on_tpu and not quick else 256
    n3d = 512 if on_tpu and not quick else 32
    steps2 = 8192 if on_tpu and not quick else 16
    steps3 = 1024 if on_tpu and not quick else 16
    hbm_mib = 256 if on_tpu else 8
    hbm_passes = 256 if on_tpu else 2  # sizing analysis in measure_hbm

    rec: dict = {"ts": time.time(), "platform": platform,
                 "device_kind": kind, "chip_class": base.name,
                 "trustworthy": bool(on_tpu),
                 "params": {"n2d": n2d, "n3d": n3d, "steps2": steps2,
                            "steps3": steps3, "hbm_mib": hbm_mib,
                            "hbm_passes": hbm_passes}}

    print(f"calibrate: platform={platform} device={kind!r} "
          f"(chip class {base.label})")
    stream = measure_hbm(mib=hbm_mib, passes=hbm_passes)
    # floor_fallback: two_point_rate hit its noise floor and returned the
    # dispatch-dominated single-call rate. On the tunneled TPU that
    # number is ~200x low — fitting with it (or emitting it in
    # chip_model) would hand the planner a poisoned cost model, which is
    # exactly what the first on-chip calibrate of round 5 did. Keep the
    # table's HBM value for the fit and the emitted model; the raw
    # measurement stays in rec["stream"] for diagnosis.
    floor_fallback = stream["floor_fallback"]
    rec["stream"] = stream
    if floor_fallback:
        # regardless of platform, so the record's labels (hbm_fitted,
        # fit_complete below) always describe what's actually in
        # chip_model — an off-TPU fallback otherwise wrote the raw rate
        # while claiming the table value stayed (review r5)
        hbm = base.hbm_bytes_per_s
        print(f"  HBM stream: overhead-dominated "
              f"({stream['hbm_bytes_per_s'] / 1e9:.1f} GB/s raw) — "
              f"keeping table value {hbm / 1e9:.0f} GB/s")
    else:
        hbm = stream["hbm_bytes_per_s"]
        print(f"  HBM stream: {hbm / 1e9:.1f} GB/s")

    chip_meas = dataclasses.replace(base, hbm_bytes_per_s=float(hbm))
    k2 = 16
    cfg2 = HeatConfig(n=n2d, ntime=steps2, dtype="float32",
                      backend="pallas", fuse_steps=k2)
    rate2, od2 = _solve_rate(cfg2)
    # an overhead-dominated rate (two-point floor fallback) must not be
    # fitted: on the tunnel it bakes ~0.15 s of dispatch into a chip
    # constant — same refusal as the HBM guard above (review r5)
    vpu = (None if od2 else
           fit_vpu_2d(1.0 / rate2, (n2d, n2d), "float32", k2, chip_meas))
    rec["sweep_2d"] = {"n": n2d, "fuse": k2, "points_per_s": rate2,
                       "overhead_dominated": od2, "vpu_ops_per_s_fit": vpu}
    print(f"  2D {n2d}^2 fuse={k2}: {rate2:.3e} pts/s -> vpu "
          f"{vpu / 1e12 if vpu else float('nan'):.2f} Tops/s"
          + (" [overhead-dominated, fit refused]" if od2 else ""))

    k3 = 8
    cfg3 = HeatConfig(n=n3d, ndim=3, ntime=steps3, dtype="float32",
                      backend="pallas", fuse_steps=k3)
    rate3, od3 = _solve_rate(cfg3)
    ops3 = (None if od3 else
            fit_ops_3d(1.0 / rate3, (n3d,) * 3, "float32", k3, chip_meas))
    rec["sweep_3d"] = {"n": n3d, "fuse": k3, "points_per_s": rate3,
                       "overhead_dominated": od3, "ops_rate_3d_fit": ops3}
    print(f"  3D {n3d}^3 fuse={k3}: {rate3:.3e} pts/s -> ops3d "
          f"{ops3 / 1e12 if ops3 else float('nan'):.2f} Tops/s"
          + (" [overhead-dominated, fit refused]" if od3 else ""))

    fitted = dataclasses.asdict(dataclasses.replace(
        base,
        name=base.name if on_tpu else f"{base.name}-proxy",
        hbm_bytes_per_s=float(hbm),
        vpu_ops_per_s=float(vpu) if vpu else base.vpu_ops_per_s,
        ops_rate_3d=float(ops3) if ops3 else base.ops_rate_3d,
        # calibrated means "every rate here is fitted from on-chip"
        # (machine.py semantics) — an HBM floor fallback leaves the
        # table value in the model, so stamping calibrated=True would
        # launder the very spec-guess this command exists to replace
        # (review r5)
        calibrated=bool(on_tpu and vpu and ops3 and not floor_fallback)))
    rec["chip_model"] = fitted
    rec["hbm_fitted"] = not floor_fallback
    rec["fit_complete"] = bool(vpu and ops3 and not floor_fallback)
    if on_tpu:
        # reproduction check against the shipped table for a KNOWN chip:
        # the acceptance bar is "reproduces the shipped constants within
        # tolerance" (VERDICT r4 #6) — report the ratios so drift is a
        # number, not a feeling
        rec["vs_table"] = {
            # None on floor fallback: a table-vs-table ratio of 1.0
            # would fake a perfect reproduction that never measured
            "hbm_ratio": (None if floor_fallback
                          else hbm / base.hbm_bytes_per_s),
            "vpu_ratio": (vpu / base.vpu_ops_per_s) if vpu else None,
            "ops3d_ratio": (ops3 / base.ops_rate_3d) if ops3 else None,
        }
        print("  vs shipped table: " + ", ".join(
            f"{k}={v:.2f}x" if v else f"{k}=n/a"
            for k, v in rec["vs_table"].items()))
    else:
        print("  NOT TRUSTWORTHY: interpret-mode rates on a non-TPU "
              "platform say nothing about any chip (harness check only)")

    with open(str(out_path) + ".tmp", "w") as f:
        json.dump(rec, f, indent=2, default=float)
    import os

    os.replace(str(out_path) + ".tmp", out_path)
    print(f"wrote {out_path}")
    if rec["fit_complete"] and rec["trustworthy"]:
        print(f"use it: HEAT_CHIP_CALIBRATION={out_path} heat-tpu run ...")
    else:
        # don't hand the operator a pointer to an incomplete/untrusted
        # record — the round-5 sweep log captured exactly that hint one
        # line above "calibrate FAILED rc=1" (review r5)
        print("record is incomplete or untrusted — NOT for "
              "HEAT_CHIP_CALIBRATION use (see fit_complete/trustworthy)")
    return rec
