from .heat import Heat2D, Heat3D, MODELS, get_model  # noqa: F401
