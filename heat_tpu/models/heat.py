"""Model definitions: the PDE problems the framework solves.

The reference solves exactly one model — 2D FTCS diffusion
(∂T/∂t = ν∇²T, fortran/serial/heat.f90:64-68) — on a square domain. The
model layer names that problem explicitly and adds the 3D 7-point extension
(BASELINE.md config 4), bundling the stability law, the step functions each
backend composes, and analytic invariants the tests check.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..config import HeatConfig


@dataclasses.dataclass(frozen=True)
class HeatModel:
    ndim: int
    stencil_points: int

    def stability_limit(self) -> float:
        """Explicit FTCS stability bound on sigma: 1/(2*ndim)."""
        return 1.0 / (2 * self.ndim)

    def is_stable(self, cfg: HeatConfig) -> bool:
        return cfg.sigma <= self.stability_limit() + 1e-12

    def steady_state(self, cfg: HeatConfig, T0=None) -> np.ndarray:
        """t→∞ limit, per BC family.

        - ``ghost``: uniform bc_value — all heat leaks through the
          Dirichlet ghost walls.
        - ``periodic``: no walls, heat conserved exactly — the uniform
          MEAN of the initial field (required as ``T0``).
        - ``edges``: the frozen ring keeps its IC values, so the limit is
          that ring's harmonic extension; this oracle covers the uniform-
          ring case (limit = the ring constant, requires ``T0``) and
          refuses the general one honestly.
        """
        if cfg.bc == "periodic":
            if T0 is None:
                raise ValueError(
                    "periodic steady state is the IC mean — pass T0")
            return np.full(cfg.shape, np.mean(np.asarray(T0, np.float64)))
        if cfg.bc == "edges":
            if T0 is None:
                raise ValueError(
                    "edges steady state is set by the frozen IC boundary "
                    "ring — pass T0")
            from ..grid import boundary_mask

            ring = np.asarray(T0, np.float64)[boundary_mask(cfg)]
            if np.ptp(ring) > 1e-12:
                raise NotImplementedError(
                    "non-uniform frozen ring: the t->inf limit is its "
                    "harmonic extension, which this constant oracle "
                    "cannot represent")
            return np.full(cfg.shape, ring.flat[0])
        return np.full(cfg.shape, cfg.bc_value)


Heat2D = HeatModel(ndim=2, stencil_points=5)
Heat3D = HeatModel(ndim=3, stencil_points=7)

MODELS = {2: Heat2D, 3: Heat3D}


def get_model(cfg: HeatConfig) -> HeatModel:
    return MODELS[cfg.ndim]
