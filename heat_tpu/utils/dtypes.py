"""Dtype plumbing.

The reference is double precision everywhere (``real*8``,
fortran/serial/heat.f90:5) with a ``SINGLE_PRECISION`` escape hatch
(fortran/hip/heat_kernel.cpp:5-9). On TPU, f64 is emulated and slow, so the
framework defaults to f32 with an f64 *parity mode* (for oracle matching,
typically on CPU) and a bf16-storage/f32-accumulate throughput mode.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_JNP = {"float64": "float64", "float32": "float32", "bfloat16": "bfloat16"}


def ensure_precision(dtype_name: str) -> None:
    """Enable jax x64 mode when an f64 run is requested."""
    if dtype_name == "float64" and not jax.config.read("jax_enable_x64"):
        jax.config.update("jax_enable_x64", True)


def jnp_dtype(dtype_name: str):
    ensure_precision(dtype_name)
    return jnp.dtype(_JNP[dtype_name])
