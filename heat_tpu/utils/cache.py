"""Per-user default for the persistent XLA compile cache.

Flagship Mosaic kernels cold-compile in minutes (benchmarks/
compile_bisect_topology.json); the persistent cache is what makes reruns
and guard-abandoned compiles pay forward. A fixed world-shared path like
``/tmp/jax_cache`` risks permission collisions and cache tampering on
multi-user hosts (ADVICE r4), so every harness default routes through
here: a per-user directory, with a user-set ``JAX_COMPILATION_CACHE_DIR``
always honored.
"""

from __future__ import annotations

import os
import sys
import tempfile


def default_cache_dir() -> str:
    """Stable per-user compile-cache path (no I/O, no directory creation —
    jax creates it on first write)."""
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    if not os.path.isabs(base):  # ~ unresolvable (no HOME): fall back to
        # a uid-suffixed tempdir, still collision-free per user
        uid = getattr(os, "getuid", lambda: "u")()
        return os.path.join(tempfile.gettempdir(), f"heat_tpu_jax_{uid}")
    return os.path.join(base, "heat_tpu", "jax")


def ensure_cache_env() -> str:
    """Set ``JAX_COMPILATION_CACHE_DIR`` to the per-user default unless the
    user already chose one; returns the effective path.

    jax snapshots the env var ONCE at import time — and importing this
    package pulls jax in transitively, so no caller can reliably run
    before that snapshot. When jax is already imported and its cache dir
    is still unset, push the default into the live config too; an env var
    or ``jax.config.update`` the user already applied is never overridden.
    Subprocesses inherit the env var either way."""
    path = os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                                 default_cache_dir())
    j = sys.modules.get("jax")
    if j is not None and j.config.jax_compilation_cache_dir is None:
        j.config.update("jax_compilation_cache_dir", path)
    return path
