from .cache import default_cache_dir, ensure_cache_env  # noqa: F401
from .dtypes import jnp_dtype, ensure_precision  # noqa: F401
