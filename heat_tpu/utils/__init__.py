from .dtypes import jnp_dtype, ensure_precision  # noqa: F401
