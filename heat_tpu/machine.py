"""Per-chip machine model: the one table of hardware constants.

Round-3 verdict (weak #5): the planner/roofline constants were v5e
values baked into three different modules — on a v5p the 2D/3D planners
would pick measurably wrong geometry and ``vs_baseline`` would silently
compare against the wrong chip's roofline (~3.4x pessimistic). This
module centralizes them, keyed by ``jax.devices()[0].device_kind``.

Calibration status matters and is carried per chip:

- **v5e**: ``calibrated=True`` — every rate here is fitted from on-chip
  measurements (rounds 1-3; see the derivation notes on each constant in
  ops/pallas_stencil.py's round-3 history and BASELINE.md).
- **v4 / v5p / v6e**: ``calibrated=False`` — HBM bandwidth is public
  spec; the effective VPU rates are the v5e fitted rates scaled by the
  public peak-compute ratio (a crude proxy: the VPU is not the MXU, so
  treat planner geometry on these chips as a starting point and
  recalibrate from a sweep). Roofline fractions on these chips are
  labeled uncalibrated in bench output.

The planner caches in ops/pallas_stencil.py key on shape/dtype only (the
chip is fixed per process); tests that override the chip must register
their planner caches here so ``override()`` can clear them.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

_MIB = 1024 * 1024


@dataclasses.dataclass(frozen=True)
class ChipModel:
    name: str                     # canonical short name ("v5e", ...)
    hbm_bytes_per_s: float        # sustained HBM bandwidth
    vpu_ops_per_s: float          # effective 2D stencil vector-op rate
    ops_rate_3d: float            # effective 3D stencil op rate
    vmem_limit_bytes: int         # Mosaic vmem_limit_bytes ceiling
    vmem_fit_bytes: int           # planner feasibility bound (headroom)
    band_budget_bytes: int        # 2D thin-band target footprint
    coltiled_band_cap_bytes: int  # col-tiled band cap (compile sanity)
    calibrated: bool              # True = rates fitted on this chip class

    def roofline_points_per_s(self, dtype) -> float:
        """Ideal one-pass-per-step HBM roofline: bytes/point/step =
        2*itemsize (read + write), the bound no one-kernel-launch-per-step
        design can exceed — BASELINE.md's vs_baseline denominator."""
        import numpy as np

        return self.hbm_bytes_per_s / (2 * np.dtype(dtype).itemsize)

    @property
    def label(self) -> str:
        return self.name + ("" if self.calibrated else " (uncalibrated)")


# v5e: all rates measured/fitted on the attached chip (rounds 1-3).
V5E = ChipModel("v5e", hbm_bytes_per_s=819e9, vpu_ops_per_s=2.2e12,
                ops_rate_3d=2.86e12, vmem_limit_bytes=110 * _MIB,
                vmem_fit_bytes=88 * _MIB, band_budget_bytes=12 * _MIB,
                coltiled_band_cap_bytes=10 * _MIB, calibrated=True)


def _scaled(name: str, hbm: float, peak_ratio: float, vmem_mib: int = 110,
            fit_mib: int = 88, band_mib: int = 12,
            coltiled_mib: int = 10) -> ChipModel:
    """Spec-derived model: public HBM number; VPU rates = v5e fitted rates
    x the public peak-compute ratio vs v5e (197 bf16 TFLOP/s)."""
    return ChipModel(
        name, hbm_bytes_per_s=hbm,
        vpu_ops_per_s=V5E.vpu_ops_per_s * peak_ratio,
        ops_rate_3d=V5E.ops_rate_3d * peak_ratio,
        vmem_limit_bytes=vmem_mib * _MIB,
        vmem_fit_bytes=fit_mib * _MIB,
        band_budget_bytes=band_mib * _MIB,
        coltiled_band_cap_bytes=coltiled_mib * _MIB,
        calibrated=False)


# public specs (jax-ml.github.io/scaling-book chip table): v4 1228 GB/s /
# 275 bf16 TFLOP/s; v5p 2765 GB/s / 459; v6e (Trillium) 1640 GB/s / 918.
# v4 VMEM is 16 MiB/core (not the 128 MiB of v5e/v5p/v6e) — the first
# spec table assumed 110 MiB and the AOT compile validator
# (benchmarks/topology_validate.py) caught it with a real
# RESOURCE_EXHAUSTED vmem verdict; bands must shrink accordingly.
_CHIPS = {
    "v5e": V5E,
    "v5p": _scaled("v5p", 2765e9, 459 / 197),
    "v4": _scaled("v4", 1228e9, 275 / 197, vmem_mib=14, fit_mib=9,
                  band_mib=2, coltiled_mib=2),
    "v6e": _scaled("v6e", 1640e9, 918 / 197),
}

# unknown device kinds (and CPU test runs) fall back to the v5e table —
# the chip this repo is calibrated on — but report uncalibrated
_DEFAULT = dataclasses.replace(V5E, calibrated=False)

_override: Optional[str] = None
_cache: Optional[ChipModel] = None
_dependent_caches: list[Callable[[], None]] = []


def classify(device_kind: str) -> ChipModel:
    """Map a jax ``device_kind`` string to a chip model. Known spellings:
    v5e reports "TPU v5 lite" / "TPU v5e"; v5p reports "TPU v5" / "TPU
    v5p"; v4 "TPU v4"; v6e "TPU v6 lite" / "TPU v6e"."""
    k = device_kind.lower().replace(" ", "")
    if "v5e" in k or "v5lite" in k:
        return _CHIPS["v5e"]
    if "v5p" in k or k.endswith("v5"):
        return _CHIPS["v5p"]
    if "v6" in k or "trillium" in k:
        return _CHIPS["v6e"]
    if "v4" in k:
        return _CHIPS["v4"]
    return _DEFAULT


def register_cache(clear: Callable[[], None]) -> None:
    """Planner caches whose entries embed chip constants register their
    cache_clear here; ``override()`` flushes them."""
    _dependent_caches.append(clear)


def from_calibration(path: str) -> ChipModel:
    """Load a ``heat-tpu calibrate`` record as the chip model. Raises on a
    malformed file (a typo'd HEAT_CHIP_CALIBRATION must fail loudly, not
    silently plan on the wrong chip). An untrustworthy record (produced on
    a non-TPU platform) is accepted but forced ``calibrated=False`` so
    every consumer labels its numbers."""
    import json

    with open(path) as f:
        rec = json.load(f)
    cm = rec["chip_model"]
    return ChipModel(**{**cm, "calibrated": bool(cm.get("calibrated")
                                                 and rec.get("trustworthy"))})


def current() -> ChipModel:
    """The chip model for this process's default device (cached: the
    attached chip cannot change mid-process; ``override`` for tests;
    ``HEAT_CHIP_CALIBRATION=<json>`` substitutes a ``heat-tpu calibrate``
    fit — the path from spec-proxy tables to fitted constants on a newly
    attached chip class)."""
    global _cache
    if _override is not None:
        return classify(_override)
    if _cache is None:
        import os

        cal = os.environ.get("HEAT_CHIP_CALIBRATION")
        if cal:
            _cache = from_calibration(cal)
            return _cache
        import jax

        try:
            kind = jax.devices()[0].device_kind
        except Exception:  # no backend at all: planner still needs numbers
            return _DEFAULT
        _cache = classify(kind) if jax.default_backend() == "tpu" else _DEFAULT
    return _cache


def override(device_kind: Optional[str]) -> None:
    """Force the chip model (tests / what-if planning). ``None`` restores
    autodetection. Flushes registered planner caches either way."""
    global _override, _cache
    _override = device_kind
    _cache = None
    for clear in _dependent_caches:
        clear()
