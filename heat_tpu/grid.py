"""Coordinates, initial conditions, and boundary conditions.

The reference builds coordinates by cumulative addition from 0 with the last
row pre-pinned to ``dom_len`` (``fortran/serial/heat.f90:28-36``); that is
``linspace(0, dom_len, n)`` up to rounding, which is what we use. Each
reference variant silently ships a *different* hat initial condition
(SURVEY.md quirk #1); they are named presets here:

- ``hat``       : T=2 on [0.5,1.5]x[0.5,1.5], else 1   (fortran/serial/heat.f90:40-48)
- ``hat_half``  : T=2 on [0.5,1.5]x[0.5,1.0], else 1   (fortran/cuda_kernel/heat.F90:98)
- ``hat_small`` : T=2 on [0.5,1.0]x[0.5,1.0], else 1   (python/serial/heat.py:25)
- ``uniform``   : T=2 everywhere — pairs with the "ghost" BC for the MPI
                  variants' uniform-hot/cold-walls setup (fortran/mpi+cuda/heat.F90:243-251)
- ``zero``      : T=0 (testing)
- ``sine``      : product of per-axis ``sin(pi * i / (n-1))`` — the
                  fundamental discrete eigenmode of the FTCS operator
                  under frozen-edge BCs (edge samples pinned to exactly
                  0). Under ``bc="edges"`` every step multiplies the
                  whole field by the closed-form factor
                  ``lambda = 1 - 4*ndim*r*sin^2(pi/(2*(n-1)))``, so step
                  s equals ``lambda**s * T0`` analytically — the
                  known-answer canary the serve prober submits
                  (serve/probe.py, ISSUE 15)

Two construction paths, bit-identical by design: ``initial_condition`` is
pure numpy on host (mirroring the reference's host-side IC plus one H2D
copy, ``fortran/mpi+cuda/heat.F90:256``) and remains the oracle; device
backends default to ``initial_condition_device``, which builds the same
field directly on device (optionally pre-sharded) so no n^d host array or
host->device transfer exists at benchmark scale.
"""

from __future__ import annotations

import math
from typing import Tuple

import numpy as np

from .config import HeatConfig

_NP_DTYPES = {"float64": np.float64, "float32": np.float32, "bfloat16": np.float32}


def np_dtype(name: str):
    """Host-side dtype; bfloat16 ICs are built in f32 and cast on device."""
    return _NP_DTYPES[name]


def coords_1d(n: int, dom_len: float, dtype=np.float64) -> np.ndarray:
    """1-D coordinate axis, 0 .. dom_len inclusive (delta = dom_len/(n-1))."""
    return np.linspace(0.0, dom_len, n, dtype=dtype)


def coords(cfg: HeatConfig) -> Tuple[np.ndarray, ...]:
    """ndim coordinate axes (all identical: square/cubic domain)."""
    ax = coords_1d(cfg.n, cfg.dom_len, np_dtype(cfg.dtype))
    return (ax,) * cfg.ndim


# (x-interval, y-interval, z-interval) of the hot region per preset; z reuses
# the y interval in 3D runs of the half/small presets.
_HAT_BOXES = {
    "hat": ((0.5, 1.5), (0.5, 1.5), (0.5, 1.5)),
    "hat_half": ((0.5, 1.5), (0.5, 1.0), (0.5, 1.0)),
    "hat_small": ((0.5, 1.0), (0.5, 1.0), (0.5, 1.0)),
}


def _sine_axis(n: int, dt) -> np.ndarray:
    """Per-axis fundamental-mode samples ``sin(pi * i/(n-1))`` with the
    two edge samples pinned to EXACTLY zero (float sin(pi) is ~1e-16, not
    0; pinning makes the frozen-edge eigenmode argument exact, not just
    near-exact). Built on host for both construction paths so the device
    field is bit-identical to the host one — libm and XLA sin need not
    agree to the last ulp, so the sine itself must only be computed
    once."""
    ax = np.sin(np.pi * np.arange(n, dtype=dt) / dt(n - 1)).astype(dt)
    ax[0] = 0.0
    ax[-1] = 0.0
    return ax


def _sine_field_np(cfg: HeatConfig, dt) -> np.ndarray:
    ax = _sine_axis(cfg.n, dt)
    out = None
    for d in range(cfg.ndim):
        sh = [1] * cfg.ndim
        sh[d] = cfg.n
        a = ax.reshape(sh)
        out = a if out is None else out * a
    return np.ascontiguousarray(np.broadcast_to(out, cfg.shape))


def sine_decay_factor(cfg: HeatConfig) -> float:
    """Closed-form per-step decay of the ``sine`` eigenmode under
    ``bc="edges"``: each FTCS update multiplies the mode by
    ``1 - 4*ndim*r*sin^2(pi/(2*(n-1)))`` (the discrete Laplacian's
    fundamental eigenvalue, LeVeque's classic analysis — PAPERS.md), so
    ``T_s = lambda**s * T0`` exactly in exact arithmetic. The serve
    prober verifies returned fields against this (serve/probe.py)."""
    lam = math.sin(math.pi / (2.0 * (cfg.n - 1))) ** 2
    return 1.0 - 4.0 * cfg.ndim * float(cfg.r) * lam


def ic_envelope(cfg: HeatConfig) -> Tuple[float, float]:
    """Analytic ``[min, max]`` of the initial field INCLUDING the
    boundary ring — the discrete-maximum-principle envelope the numerics
    observatory arms its detector with (runtime/numerics.py). Analytic
    (not a scan of T0) so mega-lane admission — which never materializes
    a host field — costs nothing. ``ghost`` BCs clamp the ring at
    ``bc_value``, which therefore joins the envelope."""
    lo, hi = {
        "uniform": (2.0, 2.0), "zero": (0.0, 0.0), "sine": (0.0, 1.0),
    }.get(cfg.ic, (1.0, 2.0))   # the hat presets: 1 background, 2 hot
    if cfg.bc == "ghost":
        lo = min(lo, cfg.bc_value)
        hi = max(hi, cfg.bc_value)
    return float(lo), float(hi)


def initial_condition(cfg: HeatConfig) -> np.ndarray:
    """Build the full initial field (including boundary/ghost-adjacent cells).

    For the "ghost" BC the returned array is the *owned* field only; the
    ghost ring (fixed at ``bc_value``) is conceptual and supplied by the halo
    exchange / boundary fill each step, matching the reference where ghosts
    are initialized once at 1.0 and global-edge ghosts never change
    (fortran/mpi+cuda/heat.F90:243-251).
    """
    dt = np_dtype(cfg.dtype)
    shape = cfg.shape
    if cfg.ic == "uniform":
        return np.full(shape, 2.0, dtype=dt)
    if cfg.ic == "zero":
        return np.zeros(shape, dtype=dt)
    if cfg.ic == "sine":
        return _sine_field_np(cfg, dt)
    box = _HAT_BOXES[cfg.ic]
    ax = coords_1d(cfg.n, cfg.dom_len, dt)
    field = np.ones(shape, dtype=dt)
    masks = []
    for d in range(cfg.ndim):
        lo, hi = box[d]
        m1 = (ax >= lo) & (ax <= hi)
        sh = [1] * cfg.ndim
        sh[d] = cfg.n
        masks.append(m1.reshape(sh))
    hot = masks[0]
    for m in masks[1:]:
        hot = hot & m
    field[np.broadcast_to(hot, shape)] = 2.0
    return field


def _hat_index_bounds(cfg: HeatConfig):
    """Per-dimension [first, last] hot-cell indices of the hat box, computed
    on host exactly as ``initial_condition`` computes its masks — so the
    device-side builder below is bit-identical to the host one."""
    box = _HAT_BOXES[cfg.ic]
    ax = coords_1d(cfg.n, cfg.dom_len, np_dtype(cfg.dtype))
    bounds = []
    for d in range(cfg.ndim):
        lo, hi = box[d]
        idx = np.nonzero((ax >= lo) & (ax <= hi))[0]
        bounds.append((int(idx[0]), int(idx[-1])) if idx.size else (1, 0))
    return bounds


def initial_condition_device(cfg: HeatConfig, sharding=None):
    """Build the initial field directly on device (optionally pre-sharded).

    Same field as ``initial_condition`` — the hat region is derived from the
    identical host-side coordinate comparison, so the two constructions
    agree bitwise — but no n^d host array is ever materialized and nothing
    crosses the host->device link. This matters at benchmark scale: the
    reference's host-IC-plus-H2D structure (fortran/mpi+cuda/heat.F90:256)
    would ship 8 GiB over the wire for the 32768^2 flagship config.
    """
    import jax
    import jax.numpy as jnp

    from .utils import jnp_dtype

    dt = jnp_dtype(cfg.dtype)
    shape = cfg.shape
    bounds = (None if cfg.ic in ("uniform", "zero", "sine")
              else _hat_index_bounds(cfg))
    # sine: the host-built axis (O(n), not O(n^d)) is the shared sine
    # computation — libm vs XLA sin need not agree bitwise, so only the
    # outer product runs on device; bfloat16 products accumulate in f32
    # and cast once, matching the host-field-then-cast path exactly
    sine_ax = _sine_axis(cfg.n, np_dtype(cfg.dtype)) if cfg.ic == "sine" else None

    def build():
        if cfg.ic == "uniform":
            return jnp.full(shape, 2.0, dtype=dt)
        if cfg.ic == "zero":
            return jnp.zeros(shape, dtype=dt)
        if cfg.ic == "sine":
            out = None
            for d in range(cfg.ndim):
                sh = [1] * cfg.ndim
                sh[d] = cfg.n
                a = jnp.asarray(sine_ax).reshape(sh)
                out = a if out is None else out * a
            return jnp.broadcast_to(out, shape).astype(dt)
        hot = None
        for d, (lo_i, hi_i) in enumerate(bounds):
            io = jax.lax.broadcasted_iota(jnp.int32, shape, d)
            m = (io >= lo_i) & (io <= hi_i)
            hot = m if hot is None else hot & m
        return jnp.where(hot, jnp.asarray(2.0, dt), jnp.asarray(1.0, dt))

    if sharding is not None:
        return jax.jit(build, out_shardings=sharding)()
    return jax.jit(build)()


def boundary_mask(cfg: HeatConfig) -> np.ndarray:
    """Boolean mask of the outermost cell ring (the frozen cells in "edges" BC,
    i.e. the cells the serial loop never touches, fortran/serial/heat.f90:64-68)."""
    mask = np.zeros(cfg.shape, dtype=bool)
    for d in range(cfg.ndim):
        sl0 = [slice(None)] * cfg.ndim
        sl1 = [slice(None)] * cfg.ndim
        sl0[d] = 0
        sl1[d] = -1
        mask[tuple(sl0)] = True
        mask[tuple(sl1)] = True
    return mask
