"""Pluggable placement policies for the fleet router.

Every function here is a pure function of :class:`~.registry.Backend`
snapshots (their last ``GET /v1/status`` payloads plus the router-local
pending accounting) and one request's parsed config — no sockets, no
clocks, no globals — so the policy unit tests feed fake status payloads
and assert on the math (tests/test_fleet_placement.py).

The default ``least-loaded`` policy ranks candidates by **predicted
backlog seconds**: the status payload's queued + running step sums plus
the router's own not-yet-acknowledged pending steps, converted to
seconds with the backend's online cost model (work-weighted EWMA
s/lane-step across its observed rows; a cold backend falls back to a
prior so relative comparison still works before any chunk has been
timed). On top of that ranking:

- **burn-aware demotion**: a backend whose fast AND slow SLO burn
  windows both exceed 1.0 for any class (the PR-8 multiwindow alert
  condition, Google SRE workbook) is demoted — it only receives work
  when every candidate is demoted, so a burning replica gets headroom
  to recover instead of more load;
- **mega routing**: a request whose side overflows a backend's buckets
  is only placed on backends advertising mega capability (the PR-10
  two-tier split lifted one level — GSPMD-style sharded mega-lanes);
- **starvation-free round-robin tiebreak**: equal-backlog candidates
  (the cold-fleet case: everyone at zero) rotate through a monotone
  router counter instead of always picking the first, so no backend
  starves while scores tie.

``round-robin`` skips the scoring entirely (health + capability filter,
then rotate) — the A/B baseline and the "my cost model is lying to me"
escape hatch.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

POLICIES = ("least-loaded", "round-robin")

# Cold-start prior: seconds per lane-step before a backend has timed a
# single chunk. The absolute value barely matters (placement compares
# backends, and cold backends all share it); it just has to be finite
# and positive so queued work on a cold backend still counts.
PRIOR_S_PER_LANE_STEP = 1e-5

# Two backlog predictions within this relative band tie (floats from
# independently-scraped payloads are never bit-equal).
TIE_REL = 0.05

BURN_THRESHOLD = 1.0


def s_per_lane_step(status: Optional[dict]) -> float:
    """Work-weighted mean EWMA s/lane-step across the backend's observed
    cost-model rows; the prior when it has observed nothing."""
    rows = (status or {}).get("cost_model") or []
    num = den = 0.0
    for e in rows:
        ew = e.get("ewma_s_per_lane_step")
        chunks = e.get("chunks") or 0
        if ew and chunks:
            num += float(ew) * int(chunks)
            den += int(chunks)
    return (num / den) if den else PRIOR_S_PER_LANE_STEP


def backlog_steps(backend) -> int:
    """Queued + running + router-pending work, in steps."""
    bl = ((backend.status or {}).get("backlog")) or {}
    return (int(bl.get("queued_steps") or 0)
            + int(bl.get("running_steps_bound") or 0)
            + int(backend.pending_steps))


def predicted_backlog_s(backend) -> float:
    """The least-loaded score: cost model x queue work, in seconds."""
    return backlog_steps(backend) * s_per_lane_step(backend.status)


def burn_demoted(status: Optional[dict],
                 threshold: float = BURN_THRESHOLD) -> bool:
    """True when any SLO class burns its error budget in BOTH windows —
    the multiwindow alert condition, used here as a placement demotion
    instead of (only) a page."""
    for b in ((status or {}).get("slo_burn") or {}).values():
        fast = b.get("fast_burn")
        slow = b.get("slow_burn")
        if (fast is not None and slow is not None
                and fast > threshold and slow > threshold):
            return True
    return False


def brownout_level(backends: List) -> int:
    """Fleet-wide brownout level for edge shedding (router dispatch).

    0 — some eligible backend is not burn-demoted: normal placement
    (demotion steers work away from the burning replicas) handles it.
    1 — EVERY eligible backend's fast AND slow burn windows fire: the
    edge sheds ``batch`` rows with Retry-After instead of placing them
    anyway (the old all-demoted passthrough behaviour for that class).
    2 — additionally the worst fast burn is at double threshold: shed
    ``standard`` too. ``interactive`` is never shed by brownout.

    Pure function of the backend snapshots so tests feed fake status
    payloads and assert the ladder directly."""
    cands = [b for b in backends
             if b.healthy and not b.fault_down and not b.lost]
    if not cands or not all(burn_demoted(b.status) for b in cands):
        return 0
    worst = 0.0
    for b in cands:
        for w in ((b.status or {}).get("slo_burn") or {}).values():
            fast = w.get("fast_burn")
            if fast is not None:
                worst = max(worst, float(fast))
    return 2 if worst >= 2 * BURN_THRESHOLD else 1


def can_serve(backend, n: Optional[int]) -> bool:
    """Capability filter: can this backend serve a side-``n`` request?
    Oversized-for-its-buckets requests need mega capability. A backend
    with no status payload yet is assumed capable (the cold-fleet case;
    the engine rejects structurally-unservable requests itself)."""
    if n is None or backend.status is None:
        return True
    mega = backend.status.get("mega") or {}
    max_bucket = int(mega.get("max_bucket") or 0)
    if max_bucket and n <= max_bucket:
        return True
    return bool(mega.get("capable"))


def eligible(backends: List, n: Optional[int]) -> List:
    """Health + capability filter shared by every policy."""
    return [b for b in backends
            if b.healthy and not b.fault_down and not b.lost
            and can_serve(b, n)]


def choose(policy: str, backends: List, n: Optional[int],
           rr_index: int, prefer=None) -> Tuple[Optional[object], Dict]:
    """Pick a backend for one side-``n`` request. Returns
    ``(backend | None, decision)`` where ``decision`` is a small dict
    for tracing/statusz (scores, who was demoted, why None).

    ``prefer`` is an optional set of backend names that should win when
    any of them is eligible — the solve-cache placement hint (a prefix
    hit wants the backend that can actually consume the cached
    frontier). A preference never overrides health/capability: when no
    preferred backend is eligible the full pool competes as usual."""
    if policy not in POLICIES:
        raise ValueError(f"unknown placement policy {policy!r}; "
                         f"known: {POLICIES}")
    cands = eligible(backends, n)
    if not cands:
        return None, {"policy": policy, "reason": "no-eligible-backend",
                      "n": n}
    preferred = False
    if prefer:
        narrowed = [b for b in cands if b.name in prefer]
        if narrowed:
            cands, preferred = narrowed, True
    if policy == "round-robin":
        b = cands[rr_index % len(cands)]
        return b, {"policy": policy, "backend": b.name,
                   **({"preferred": True} if preferred else {})}
    demoted = [b.name for b in cands if burn_demoted(b.status)]
    pool = [b for b in cands if b.name not in demoted] or cands
    scores = {b.name: predicted_backlog_s(b) for b in pool}
    best = min(scores.values())
    tied = [b for b in pool
            if scores[b.name] <= best + TIE_REL * max(best, 1e-9)]
    b = tied[rr_index % len(tied)]
    return b, {"policy": policy, "backend": b.name,
               "backlog_s": {k: round(v, 6) for k, v in scores.items()},
               "demoted": demoted,
               **({"preferred": True} if preferred else {})}
